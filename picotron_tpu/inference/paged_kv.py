"""Paged KV cache: a global page pool with refcounted prefix sharing + COW.

The contiguous cache (inference/kv_cache.py) gives every slot its own
``max_seq_len`` strip, so HBM capacity is ``slots x max window`` no matter
how short the live sequences are — and two requests with the same system
prompt each prefill and store their own copy of it. This module replaces
the strip with **block-table indirection** over a global pool of
fixed-size KV pages (vLLM's PagedAttention layout) and builds **radix
prefix sharing** on top (SGLang's RadixAttention):

- **Device layout** (``init_cache``): the per-layer cache leaves become a
  page pool ``k``/``v``: ``[num_layers, num_pages, page_len, n_kv_heads,
  head_dim]`` (int8 mode adds ``k_scale``/``v_scale``
  ``[L, P, page_len, Hkv]`` exactly like the contiguous layout), plus
  ``block_tables [slots, max_pages_per_slot] int32`` mapping each slot's
  logical page index to a pool page, and the same ``lengths [slots]``.
  Page 0 is the reserved NULL page: unallocated table entries point at it
  and every out-of-window or masked write is redirected into it, so a
  bad index can scribble only on bytes nothing ever reads.
- **Host allocator** (``PagePool`` / ``PagedKV``): a free list plus a
  refcount per page. A page's refcount is the number of holders — each
  slot whose block table points at it, plus the radix cache when the page
  backs a cached prefix. Slots allocate lazily as their sequences grow
  (``ensure_writable``), release returns every held page
  (refcount-aware), and a write into a page with refcount > 1 first
  **copies-on-write**: the writer gets a fresh copy (``copy_page``, a
  byte-exact device copy) and drops its reference, so shared bytes are
  immutable for as long as anyone shares them.
- **Prefix sharing** (``RadixCache``): a trie over page-sized token
  chunks. After a prompt prefills, its prompt pages are inserted (the
  cache takes a reference); a new request walks the trie, reuses the
  pages of its longest cached prefix (bumping refcounts — zero prefill
  work for those tokens), and prefills only the suffix. The match may
  end mid-page (a fork point): the request shares the tail page too, and
  its first write past the fork triggers the COW above. Refcount-1
  leaves (held by nobody but the cache) are evicted LRU-first when the
  pool runs dry.

Correctness contract: K/V rows at position ``p`` depend only on tokens
``0..p`` (causal attention; the chunked-prefill overlap re-feed already
relies on this), so a cached page whose token path matches a request's
prompt prefix holds exactly the bytes that request's own prefill would
have written — sharing changes WHERE bytes live, never what they are.
The attend paths consume the indirection without changing math: the
dense path gathers the slot's pages into a contiguous window and runs
the same masked einsum (bit-identical — masked columns contribute exact
zeros), the flash kernel walks ``block_tables[b, i]`` pages instead of
contiguous blocks (ops/pallas/decode_attention.py). Selected by
``inference.kv_layout: "paged"``; tests/test_paged_kv.py pins paged
generations against contiguous across every dispatch family.
"""

from __future__ import annotations

from collections import deque

import jax.numpy as jnp
import numpy as np
from jax import lax

from picotron_tpu.config import ModelConfig
from picotron_tpu.inference import kv_cache

# table entries start here; page 0 is the reserved NULL page (never
# allocated, the target of masked/out-of-window writes)
NULL_PAGE = 0


class PagePoolExhausted(RuntimeError):
    """No free page and nothing evictable — the caller sheds, it never
    corrupts a live slot."""


# --------------------------------------------------------------------------- #
# device ops (jitted by the engine)
# --------------------------------------------------------------------------- #


def cache_pspecs(quantized: bool = False, policy: bool = False,
                 dp: int = 1) -> dict:
    """PartitionSpecs of the paged cache pytree: identical to the
    contiguous layout's (the kv-head axis of the pool — and of the int8
    scale tensors — shards over 'tp'; page axes are replicated at dp=1),
    plus ``block_tables``. On a dp-sharded serving mesh (``dp > 1``) the
    POOL PAGE axis shards over 'dp' — each dp shard owns
    ``num_pages / dp`` pages holding only its own slots' K/V — and the
    per-slot ``block_tables``/``lengths`` rows shard with their slots.
    The ``hot_bf16`` policy adds the int8 side pool (``k_q``/``v_q`` +
    scales, same sharding) and the per-page ``page_quant`` flags."""
    from jax.sharding import PartitionSpec as P

    slot_ax = "dp" if dp > 1 else None
    specs = kv_cache.cache_pspecs(quantized, dp=dp)
    specs["block_tables"] = P(slot_ax, None) if dp > 1 else P()
    if policy:
        kv = P(None, slot_ax, None, "tp", None)
        scale = P(None, slot_ax, None, "tp")
        specs.update(k_q=kv, v_q=kv, k_scale=scale, v_scale=scale,
                     page_quant=P(slot_ax) if dp > 1 else P())
    return specs


# cache leaves with no layer axis: host-owned page metadata that rides as
# a scan constant through the engine's layer scan and is skipped by every
# per-page device op (copy_page slices the page axis, which these lack)
META_LEAVES = ("lengths", "block_tables", "page_quant")


def is_policy(cache: dict) -> bool:
    """Whether a cache pytree (full or per-layer) carries the hot_bf16
    dual-representation pool."""
    return "k_q" in cache


def init_cache(m: ModelConfig, slots: int, num_pages: int, page_len: int,
               max_pages: int, dtype=None, quantized: bool = False,
               policy: bool = False) -> dict:
    """Zeroed page pool + NULL block tables + zero lengths. Same dtype
    rules as the contiguous ``kv_cache.init_cache``. ``policy`` (the
    ``hot_bf16`` per-page policy) adds the int8 side pool: every write
    lands in BOTH representations and the per-page ``page_quant`` flag —
    refreshed from the host allocator's refcounts before each dispatch —
    selects which one the attend READS, so a page can flip between hot
    (full precision) and cold (int8) as sharing changes without ever
    rewriting bytes. (This reference implementation keeps both
    representations resident; a hardware allocator would partition one
    arena and demote pages physically — staged exactly like the dense/
    contiguous serving defaults.)"""
    shape = (m.num_hidden_layers, num_pages, page_len,
             m.num_key_value_heads, m.head_dim)
    if quantized:
        if policy:
            raise ValueError(
                "hot_bf16 page policy is mutually exclusive with a "
                "uniformly int8 cache (config.validate names the fix)")
        cache = {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(shape[:-1], kv_cache.SCALE_DTYPE),
            "v_scale": jnp.zeros(shape[:-1], kv_cache.SCALE_DTYPE),
        }
    else:
        dt = jnp.dtype(dtype if dtype is not None else m.dtype)
        cache = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
        if policy:
            cache.update({
                "k_q": jnp.zeros(shape, jnp.int8),
                "v_q": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(shape[:-1], kv_cache.SCALE_DTYPE),
                "v_scale": jnp.zeros(shape[:-1], kv_cache.SCALE_DTYPE),
                "page_quant": jnp.zeros((num_pages,), jnp.int32),
            })
    cache["block_tables"] = jnp.full((slots, max_pages), NULL_PAGE,
                                     jnp.int32)
    cache["lengths"] = jnp.zeros((slots,), jnp.int32)
    return cache


def _targets(bt: jnp.ndarray, rows: jnp.ndarray, page_len: int):
    """Map logical row positions to (pool page, in-page offset) through a
    block table. ``bt`` [..., max_pages], ``rows`` [..., S] global
    positions. Rows outside the paged window redirect to the NULL page at
    offset 0 (mirroring the contiguous scatter's drop semantics — those
    rows are never visible either way)."""
    maxp = bt.shape[-1]
    valid = (rows >= 0) & (rows < maxp * page_len)
    page_idx = jnp.clip(rows // page_len, 0, maxp - 1)
    pid = jnp.take_along_axis(bt, page_idx, axis=-1)
    pid = jnp.where(valid, pid, NULL_PAGE)
    off = jnp.where(valid, rows % page_len, 0)
    return pid, off


def cache_write(layer_cache: dict, k_new: jnp.ndarray, v_new: jnp.ndarray,
                pos: jnp.ndarray) -> dict:
    """Paged counterpart of ``kv_cache.cache_write``: scatter each slot's
    S fresh rows through its block-table row. One generic gather+scatter
    serves all three write shapes (decode S=1, verify B>1 S>1, chunked
    prefill B=1 S=C) — row ``pos[b] + s`` lands in pool page
    ``bt[b, (pos+s) // page_len]`` at offset ``(pos+s) % page_len``.
    Out-of-window rows (and free slots' NULL table entries) write the
    NULL page. int8 caches quantize on write exactly like the contiguous
    path. The host allocator guarantees every page this can touch is
    exclusively owned by the writing slot (COW ran before the dispatch),
    so a shared page's bytes are never mutated — including by the
    speculative verify's optimistic writes that a rollback later strands.

    A ``draft_valid`` [B] int32 entry (the ragged-verify mask, spliced
    per dispatch — see kv_cache.cache_write) caps each slot's write at
    its own real-token count: masked rows are pushed out of the logical
    window, which ``_targets`` routes to the NULL page.
    """
    out = dict(layer_cache)
    valid = out.pop("draft_valid", None)
    B, S = k_new.shape[0], k_new.shape[1]
    bt = layer_cache["block_tables"]  # [B, max_pages] int32
    page_len = layer_cache["k"].shape[1]
    rows = pos[:, None].astype(jnp.int32) + jnp.arange(S, dtype=jnp.int32)
    if valid is not None and S > 1:
        cols = jnp.arange(S, dtype=jnp.int32)[None, :]
        rows = jnp.where(cols < valid[:, None], rows,
                         bt.shape[-1] * page_len)
    pid, off = _targets(bt, rows, page_len)  # [B, S] each
    policy = is_policy(layer_cache)

    def store(name, qname, sname, new):
        if policy:
            # hot_bf16 dual write: the fresh rows land in BOTH pool
            # representations (full precision + int8 with scales), so the
            # per-page flag can flip as sharing changes without rewriting
            # bytes — the read side (attend) selects per page. Write
            # traffic is S rows per dispatch, noise next to the attend's
            # window read the policy halves.
            qvals, scales = kv_cache.quantize_kv(new)
            out[name] = layer_cache[name].at[pid, off].set(
                new.astype(layer_cache[name].dtype))
            out[qname] = layer_cache[qname].at[pid, off].set(qvals)
            out[sname] = layer_cache[sname].at[pid, off].set(
                scales.astype(kv_cache.SCALE_DTYPE))
            return
        if kv_cache.quantized(layer_cache):
            vals, scales = kv_cache.quantize_kv(new)
        else:
            vals, scales = new.astype(layer_cache[name].dtype), None
        out[name] = layer_cache[name].at[pid, off].set(vals)
        if scales is not None:
            out[sname] = layer_cache[sname].at[pid, off].set(
                scales.astype(kv_cache.SCALE_DTYPE))

    store("k", "k_q", "k_scale", k_new)
    store("v", "v_q", "v_scale", v_new)
    return out


def gather_window(pool: jnp.ndarray, bt: jnp.ndarray) -> jnp.ndarray:
    """Materialize slots' logical windows from the pool: ``pool``
    [P, page_len, ...] + ``bt`` [B, max_pages] -> [B, max_pages *
    page_len, ...] — the contiguous view the dense reference attend
    consumes. (The flash kernel never materializes this; it walks the
    table page by page.)"""
    g = pool[bt]  # [B, max_pages, page_len, ...]
    return g.reshape((bt.shape[0], -1) + pool.shape[2:])


def attend(q: jnp.ndarray, layer_cache: dict, lengths: jnp.ndarray,
           scale: float, impl: str = "dense") -> jnp.ndarray:
    """Masked attention of S fresh queries against one layer's paged
    cache. "dense" gathers the slots' pages into a contiguous window and
    runs the bit-pinned ``kv_cache.decode_attention`` (int8 first
    dequantizes the gathered window to fp32, the same reference
    discipline as contiguous dense); "flash" hands the pool + block
    tables to the Pallas kernel, which DMAs pages straight from HBM —
    no gathered window ever exists on that path."""
    bt = layer_cache["block_tables"]
    policy = is_policy(layer_cache)
    if impl == "flash":
        from picotron_tpu.ops.pallas.decode_attention import (
            flash_decode_attention,
        )
        from picotron_tpu.utils import on_tpu

        if policy:
            # mixed-precision page read: the per-page flag — gathered
            # through the block table into [B, max_pages] SMEM rows —
            # decides which pool representation each page's DMA fetches
            return flash_decode_attention(
                q, layer_cache["k"], layer_cache["v"], lengths, scale,
                k_quant=layer_cache["k_q"], v_quant=layer_cache["v_q"],
                k_scale=layer_cache["k_scale"],
                v_scale=layer_cache["v_scale"],
                block_tables=bt,
                block_quant=jnp.take(layer_cache["page_quant"], bt, axis=0),
                interpret=not on_tpu())
        return flash_decode_attention(
            q, layer_cache["k"], layer_cache["v"], lengths, scale,
            k_scale=layer_cache.get("k_scale"),
            v_scale=layer_cache.get("v_scale"),
            block_tables=bt, interpret=not on_tpu())
    if impl != "dense":
        raise ValueError(f"unknown attend impl {impl!r} (dense|flash)")
    k = gather_window(layer_cache["k"], bt)
    v = gather_window(layer_cache["v"], bt)
    if policy:
        # mixed dense read (the bit-pinned reference for the flash DMA
        # path above): gather both representations' windows, dequantize
        # the int8 one, and select per page — rows of a flagged page come
        # from the quantized bytes, exactly what the kernel DMAs
        page_len = layer_cache["k"].shape[1]
        flags = jnp.repeat(jnp.take(layer_cache["page_quant"], bt, axis=0),
                           page_len, axis=1)  # [B, max_pages*page_len]
        quant = (flags != 0)[..., None, None]
        kq = kv_cache.dequantize_kv(
            gather_window(layer_cache["k_q"], bt),
            gather_window(layer_cache["k_scale"], bt), jnp.float32)
        vq = kv_cache.dequantize_kv(
            gather_window(layer_cache["v_q"], bt),
            gather_window(layer_cache["v_scale"], bt), jnp.float32)
        k = jnp.where(quant, kq, k.astype(jnp.float32))
        v = jnp.where(quant, vq, v.astype(jnp.float32))
    elif kv_cache.quantized(layer_cache):
        k = kv_cache.dequantize_kv(
            k, gather_window(layer_cache["k_scale"], bt), jnp.float32)
        v = kv_cache.dequantize_kv(
            v, gather_window(layer_cache["v_scale"], bt), jnp.float32)
    return kv_cache.decode_attention(q, k, v, lengths, scale)


def insert_prefill(cache: dict, kv: dict, slot, length) -> dict:
    """Park a one-shot prefill's ``[L, 1, S_bucket, H, D]`` blocks into
    ``slot``'s pages and set its length — the paged ``insert``. Pad rows
    beyond ``length`` (and rows whose page was never allocated) write the
    NULL page. ``slot``/``length`` may be traced — one compile per bucket
    size, like the contiguous path."""
    slot = jnp.asarray(slot, jnp.int32)
    length = jnp.asarray(length, jnp.int32)
    bt = cache["block_tables"]
    row = lax.dynamic_slice_in_dim(bt, slot, 1, axis=0)  # [1, max_pages]
    S = kv["k"].shape[2]
    page_len = cache["k"].shape[2]
    rows = jnp.arange(S, dtype=jnp.int32)[None, :]  # [1, S]
    rows = jnp.where(rows < length, rows, -1)  # pad rows -> NULL page
    pid, off = _targets(row, rows, page_len)
    pid, off = pid[0], off[0]  # [S]

    def put(name):
        dst = cache[name]
        src = kv[name][:, 0].astype(dst.dtype)  # [L, S, ...]
        return dst.at[:, pid, off].set(src)

    out = {name: put(name) for name in cache if name not in META_LEAVES}
    for name in META_LEAVES:
        if name in cache:
            out[name] = cache[name]
    out["lengths"] = cache["lengths"].at[slot].set(length)
    return out


def slice_page(cache: dict, pid) -> dict:
    """One pool page's storage leaves as ``[L, page_len, ...]`` arrays —
    the single-page read (tests/debug). ``pid`` may be a traced scalar:
    one compiled executable serves every page, exactly like
    ``copy_page``."""
    pid = jnp.asarray(pid, jnp.int32)
    return {name: lax.dynamic_slice_in_dim(a, pid, 1, axis=1)[:, 0]
            for name, a in cache.items() if name not in META_LEAVES}


def gather_pages(cache: dict, pids: jnp.ndarray) -> dict:
    """A batch of pool pages' storage leaves as ``[n, L, page_len, ...]``
    arrays (page-major, matching ``write_pages``' input) — the export
    half of the page transport in ONE dispatch + ONE host sync, however
    long the prefix. The caller pads ``pids`` to a pow-2 bucket with
    NULL-page entries (free reads of bytes nothing cares about), so a
    handful of compiled shapes serve every export size."""
    pids = jnp.asarray(pids, jnp.int32)
    return {name: jnp.moveaxis(jnp.take(a, pids, axis=1), 1, 0)
            for name, a in cache.items() if name not in META_LEAVES}


def write_pages(cache: dict, pages: dict, pids: jnp.ndarray) -> dict:
    """Write a batch of imported pages' storage leaves into pool pages
    ``pids`` — the import half of the page transport, ONE dispatch per
    import. ``pages[name]`` is ``[n, L, page_len, ...]`` (page-major so
    the host stacks payload pages directly); ``pids`` is ``[n]`` int32.
    The caller pads ``n`` to a pow-2 bucket with NULL-page targets —
    page 0 is the designated scribble target nothing ever reads — so a
    handful of compiled shapes serve every import size. Byte-exact: the
    transport validated dtypes before this runs, so the astype is an
    identity guard, never a conversion."""
    pids = jnp.asarray(pids, jnp.int32)
    out = dict(cache)
    for name, a in pages.items():
        out[name] = cache[name].at[:, pids].set(
            jnp.moveaxis(a, 0, 1).astype(cache[name].dtype))
    return out


def copy_page(cache: dict, src, dst) -> dict:
    """Byte-exact pool-page copy across every layer and every storage
    leaf (K, V, scales) — the device half of copy-on-write. ``src``/
    ``dst`` may be traced scalars: one compiled executable serves every
    copy."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    out = dict(cache)
    for name, a in cache.items():
        if name in META_LEAVES:
            continue
        page = lax.dynamic_slice_in_dim(a, src, 1, axis=1)
        out[name] = lax.dynamic_update_slice_in_dim(a, page, dst, axis=1)
    return out


def set_length(cache: dict, slot, length) -> dict:
    """Set one slot's length pointer (admission of a shared prefix: the
    slot's visible history becomes the cached pages, no prefill ran)."""
    return {**cache, "lengths": cache["lengths"].at[slot].set(
        jnp.asarray(length, jnp.int32))}


def slot_rows(cache: dict, tables: np.ndarray, slot: int, n: int,
              name: str = "k") -> np.ndarray:
    """Test/debug helper: read back slot ``slot``'s first ``n`` logical
    rows of storage leaf ``name`` as [L, n, ...] host arrays, resolving
    the page indirection through the HOST table copy."""
    pool = np.asarray(cache[name])
    plen = pool.shape[2]
    out = []
    for r in range(n):
        pid = int(tables[slot, r // plen])
        out.append(pool[:, pid, r % plen])
    return np.stack(out, axis=1)


# --------------------------------------------------------------------------- #
# host-side allocator
# --------------------------------------------------------------------------- #


class PagePool:
    """Free list + refcounts over ``num_pages`` pool pages. Page 0 is
    reserved (NULL) and never allocated. A page's refcount counts its
    holders — slots whose tables point at it plus the radix cache —
    and the page returns to the free list exactly when the count hits 0.
    Deterministic FIFO allocation order (tests replay it)."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        self.num_pages = int(num_pages)
        self.refs = np.zeros(self.num_pages, np.int32)
        self.refs[NULL_PAGE] = 1  # permanently held, never freed
        self._free: deque = deque(range(1, self.num_pages))

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def usable_pages(self) -> int:
        """Pages that can ever hold data (everything but NULL)."""
        return self.num_pages - 1

    @property
    def live_count(self) -> int:
        return self.usable_pages - self.free_count

    @property
    def shared_count(self) -> int:
        """Pages with more than one holder (prefix sharing in effect)."""
        return int(np.sum(self.refs[1:] > 1))

    def alloc(self):
        """Pop a free page at refcount 1, or None when the pool is dry
        (the caller evicts or sheds — alloc itself never raises)."""
        if not self._free:
            return None
        pid = self._free.popleft()
        assert self.refs[pid] == 0
        self.refs[pid] = 1
        return pid

    def ref(self, pid: int) -> None:
        """Add a holder. Refusing to resurrect a freed page (refcount 0)
        is what makes use-after-free a loud error instead of corruption."""
        if pid == NULL_PAGE:
            raise ValueError("cannot take a reference on the NULL page")
        if self.refs[pid] <= 0:
            raise ValueError(f"page {pid} is free; ref would resurrect it")
        self.refs[pid] += 1

    def unref(self, pid: int) -> bool:
        """Drop a holder; returns True when this freed the page. A drop
        below zero is a double free — raised, never masked."""
        if pid == NULL_PAGE:
            raise ValueError("cannot drop a reference on the NULL page")
        if self.refs[pid] <= 0:
            raise ValueError(f"double free of page {pid}")
        self.refs[pid] -= 1
        if self.refs[pid] == 0:
            self._free.append(pid)
            return True
        return False


class _Node:
    """One radix-cache node: a pool page holding the K/V rows of
    ``tokens`` (a full ``page_len`` chunk for interior nodes, shorter for
    partial leaves at prompt tails)."""

    __slots__ = ("tokens", "page_id", "parent", "children", "last_use")

    def __init__(self, tokens: tuple, page_id: int, parent):
        self.tokens = tokens
        self.page_id = page_id
        self.parent = parent
        self.children: dict = {}
        self.last_use = 0


class RadixCache:
    """Prefix trie over page-sized token chunks -> pool pages.

    ``match`` walks full-page chunks by exact lookup, then closes with
    the best partial overlap among the children at the divergence point —
    the page backing that overlap is shared too, and the sharer's first
    write past the fork COWs it. ``insert`` registers a prefilled
    prompt's pages (the cache becomes a holder: refcount +1). Eviction is
    LRU over refcount-1 leaves (pages nobody but the cache holds);
    freeing a leaf can expose its parent as the next candidate.

    Every lookup/registration operation takes a ``salt`` (default ``""``)
    naming an isolation domain — multi-tenant serving salts with the
    tenant id so identical prompts under different tenants NEVER share
    pages (a cross-tenant prefix hit would leak one tenant's KV bytes
    into another's decode). Each salt owns its own trie root; eviction
    and accounting span all of them, so an idle tenant's cached prefixes
    still yield to a busy one under pressure."""

    def __init__(self, page_len: int, pool: PagePool):
        self.page_len = int(page_len)
        self.pool = pool
        self.root = _Node((), -1, None)  # the default ("") salt's root
        self._roots = {"": self.root}
        self._clock = 0
        self.evictions = 0

    def _root_for(self, salt: str) -> _Node:
        root = self._roots.get(salt)
        if root is None:
            root = _Node((), -1, None)
            self._roots[salt] = root
        return root

    def _touch(self, node: _Node) -> None:
        self._clock += 1
        node.last_use = self._clock

    @staticmethod
    def _overlap(a, b) -> int:
        n = 0
        for x, y in zip(a, b):
            if x != y:
                break
            n += 1
        return n

    def match(self, ids, salt: str = "") -> tuple:
        """Longest cached prefix of ``ids`` within ``salt``'s domain:
        returns (pages, matched) where ``pages`` back positions
        ``[0, matched)`` in order (the last may be partial: ``matched``
        can end mid-page)."""
        node, pages, matched = self._root_for(salt), [], 0
        rest = list(ids)
        while True:
            chunk = tuple(rest[: self.page_len])
            child = (node.children.get(chunk)
                     if len(chunk) == self.page_len else None)
            if child is not None and len(child.tokens) == self.page_len:
                pages.append(child.page_id)
                matched += self.page_len
                rest = rest[self.page_len:]
                self._touch(child)
                node = child
                continue
            best, bj = None, 0
            for c in node.children.values():
                j = self._overlap(c.tokens, rest)
                if j > bj:
                    best, bj = c, j
            if best is not None:
                pages.append(best.page_id)
                matched += bj
                self._touch(best)
            return pages, matched

    def insert(self, ids, page_at, salt: str = "") -> int:
        """Register a prefilled prompt's pages under ``salt``'s domain:
        ``page_at(i)`` resolves the prompt's logical page ``i`` (the
        slot's table). Existing nodes are touched, new ones take a cache
        reference on the slot's page. The partial tail (a prompt ending
        mid-page) becomes a partial leaf unless an existing child already
        covers it. Returns the number of nodes created."""
        node, created = self._root_for(salt), 0
        n = len(ids)
        full = n // self.page_len
        for i in range(full):
            chunk = tuple(ids[i * self.page_len:(i + 1) * self.page_len])
            child = node.children.get(chunk)
            if child is None:
                pid = page_at(i)
                if pid == NULL_PAGE or self.pool.refs[pid] != 1:
                    # not exclusively the slot's (window edge oddities);
                    # stop registering rather than freeze a moving page
                    return created
                child = _Node(chunk, pid, node)
                node.children[chunk] = child
                self.pool.ref(pid)
                created += 1
            self._touch(child)
            node = child
        tail = tuple(ids[full * self.page_len:])
        if tail:
            for c in node.children.values():
                if self._overlap(c.tokens, tail) == len(tail):
                    return created  # an existing child already covers it
            pid = page_at(full)
            if pid != NULL_PAGE and self.pool.refs[pid] == 1:
                leaf = _Node(tail, pid, node)
                node.children[tail] = leaf
                self.pool.ref(pid)
                self._touch(leaf)
                created += 1
        return created

    def plan_adopt(self, ids, salt: str = "") -> list:
        """Chunk indices of ``ids`` with no existing trie node in
        ``salt``'s domain — the pages a cross-replica import must supply
        (non-destructive dry run of ``adopt``). Once one chunk is
        missing, every deeper chunk needs a node too (its parent path
        would be new), so the plan is always a suffix of the chunk
        list."""
        node = self._root_for(salt)
        n = len(ids)
        full = n // self.page_len
        tail = n % self.page_len
        total = full + (1 if tail else 0)
        for i in range(full):
            chunk = tuple(ids[i * self.page_len:(i + 1) * self.page_len])
            child = node.children.get(chunk)
            if child is None:
                return list(range(i, total))
            node = child
        if tail:
            t = tuple(ids[full * self.page_len:])
            if not any(self._overlap(c.tokens, t) == len(t)
                       for c in node.children.values()):
                return [full]
        return []

    def adopt(self, ids, page_for: dict, salt: str = "") -> tuple:
        """Graft imported pages into ``salt``'s trie: ``page_for[i]`` backs
        chunk ``i`` of ``ids`` (the last may be partial). New nodes take a
        cache reference on their page (the importer's own alloc reference
        is dropped by the caller afterwards, leaving exactly the cache as
        holder — the same end state as a slot's ``register_prompt``).
        Chunks that already have a node are touched and their imported
        page (if any was supplied) is returned in ``dups`` for the caller
        to free — idempotent under the dispatch-retry discipline. Returns
        (created, duplicate_page_ids)."""
        node, created, dups = self._root_for(salt), 0, []
        n = len(ids)
        full = n // self.page_len
        for i in range(full):
            chunk = tuple(ids[i * self.page_len:(i + 1) * self.page_len])
            child = node.children.get(chunk)
            if child is not None:
                if i in page_for:
                    dups.append(page_for[i])
                self._touch(child)
                node = child
                continue
            if i not in page_for:
                # a gap the import cannot fill (the plan predates a
                # concurrent eviction): stop grafting, free nothing here
                return created, dups
            child = _Node(chunk, page_for[i], node)
            node.children[chunk] = child
            self.pool.ref(page_for[i])
            self._touch(child)
            node = child
            created += 1
        tail = tuple(ids[full * self.page_len:])
        if tail and full in page_for:
            if any(self._overlap(c.tokens, tail) == len(tail)
                   for c in node.children.values()):
                dups.append(page_for[full])
            else:
                leaf = _Node(tail, page_for[full], node)
                node.children[tail] = leaf
                self.pool.ref(page_for[full])
                self._touch(leaf)
                created += 1
        return created, dups

    def _leaves(self):
        for root in self._roots.values():
            stack = list(root.children.values())
            while stack:
                n = stack.pop()
                if not n.children:
                    yield n
                stack.extend(n.children.values())

    def cached_prefixes(self, limit: int = 4) -> list:
        """The hottest cached token prefixes across every isolation
        domain: ``[(salt, ids)]`` for the ``limit`` most recently used
        leaves, hottest first. A leaf's root path IS a maximal cached
        prefix (interior nodes are covered by their descendants), so
        these are exactly what a drain-time cache handoff
        (tools/fleet.py) should export through the page transport."""
        scored = []
        for salt, root in self._roots.items():
            stack = list(root.children.values())
            while stack:
                n = stack.pop()
                if n.children:
                    stack.extend(n.children.values())
                    continue
                ids: list = []
                node = n
                while node is not None and node.parent is not None:
                    ids[:0] = node.tokens
                    node = node.parent
                scored.append((n.last_use, salt, ids))
        scored.sort(key=lambda t: t[0], reverse=True)
        return [(salt, ids) for _, salt, ids in scored[:max(0, limit)]]

    def evictable_count(self) -> int:
        """Pages eviction could free, cascading: nodes whose ENTIRE
        subtree is held only by the cache (freeing a leaf exposes its
        parent, so a refcount-1 chain frees end to end). Counting the
        cascade — not just today's leaves — is what keeps admission from
        deadlocking behind a deep cached prefix when no slot holds it."""

        def count(n: _Node) -> tuple:
            total, free = 0, True
            for c in n.children.values():
                ct, cf = count(c)
                total += ct
                free = free and cf
            if not free or self.pool.refs[n.page_id] != 1:
                return total, False
            return total + 1, True

        return sum(count(c)[0] for root in self._roots.values()
                   for c in root.children.values())

    def evict_one(self) -> bool:
        """Free the least-recently-used refcount-1 leaf's page. Returns
        False when nothing is evictable (every cached page is also held
        by a live slot)."""
        best = None
        for n in self._leaves():
            if self.pool.refs[n.page_id] == 1 and (
                    best is None or n.last_use < best.last_use):
                best = n
        if best is None:
            return False
        self.pool.unref(best.page_id)
        del best.parent.children[best.tokens]
        self.evictions += 1
        return True

    def clear(self) -> None:
        """Drop every cache reference, across all salts (pool reset
        path)."""
        stack = [n for root in self._roots.values()
                 for n in root.children.values()]
        while stack:
            n = stack.pop()
            self.pool.unref(n.page_id)
            stack.extend(n.children.values())
        for root in self._roots.values():
            root.children = {}


class PagedKV:
    """Host-side page manager for one engine: per-slot block tables +
    lengths, the pool, the radix cache, and admission pricing.

    The engine consults it before every dispatch (``ensure_writable`` —
    allocate growth pages, COW shared ones), mirrors device length
    advancement after (``advance``/``set_len``), and frees on slot
    release. The batcher prices admission in pages against
    ``can_admit`` so decode-time allocation is never the thing that
    discovers overload. ``tables`` is the numpy master the engine ships
    to the device before each dispatch."""

    def __init__(self, slots: int, page_len: int, max_pages: int,
                 num_pages: int, prefix_cache: bool = True):
        self.slots = int(slots)
        self.page_len = int(page_len)
        self.max_pages = int(max_pages)
        self.num_pages = int(num_pages)
        self.prefix_cache = bool(prefix_cache)
        self.reset()

    def reset(self) -> None:
        """Fresh pool/trie/tables — pairs with a fresh zeroed device
        cache (engine.init_cache), including the batcher's cache-lost
        rebuild."""
        self.pool = PagePool(self.num_pages)
        self.radix = RadixCache(self.page_len, self.pool)
        self.tables = np.full((self.slots, self.max_pages), NULL_PAGE,
                              np.int32)
        self.host_len = np.zeros(self.slots, np.int64)
        self.priced = np.zeros(self.slots, np.int64)
        # prefix-cache effectiveness counters (stats())
        self.prefix_queries = 0
        self.prefix_hits = 0
        self.prompt_tokens = 0
        self.cached_tokens = 0
        self.cow_copies = 0

    # ---- pricing / admission ---------------------------------------------

    def pages_for(self, tokens: int) -> int:
        """Worst-case pages ``tokens`` rows can occupy."""
        return -(-max(int(tokens), 0) // self.page_len)

    @property
    def usable_pages(self) -> int:
        return self.pool.usable_pages

    def future_need(self) -> int:
        """Pages the live slots may still demand: each priced slot can
        grow (and COW) until every page of its worst-case commitment is
        exclusively its own, so only exclusively-held pages discharge the
        debt. Conservative by construction — shared full-prefix pages are
        never actually COW'd, but counting them keeps decode-time
        allocation from ever being the thing that discovers overload."""
        need = 0
        for s in range(self.slots):
            if self.priced[s] <= 0:
                continue
            exclusive = sum(1 for pid in self.tables[s]
                            if pid != NULL_PAGE and self.pool.refs[pid] == 1)
            need += max(0, int(self.priced[s]) - exclusive)
        return need

    def available_pages(self) -> int:
        """Pages an incoming request could claim right now: free +
        immediately evictable, minus what live slots are still owed."""
        return (self.pool.free_count + self.radix.evictable_count()
                - self.future_need())

    def can_admit(self, need: int, slot: int = None) -> bool:
        """Whether ``need`` pages are claimable right now. ``slot`` is
        accepted (and ignored) for signature parity with the dp-sharded
        manager, where admission capacity is per-shard."""
        return need <= self.available_pages()

    # ---- slot lifecycle ---------------------------------------------------

    def _alloc(self) -> int:
        pid = self.pool.alloc()
        while pid is None:
            if not self.radix.evict_one():
                raise PagePoolExhausted(
                    f"page pool exhausted ({self.pool.usable_pages} pages, "
                    f"none free or evictable)")
            pid = self.pool.alloc()
        return pid

    def match_prefix(self, slot: int, ids, cap_last: bool = True,
                     salt: str = "") -> int:
        """Admission half of prefix sharing: find the longest cached
        prefix of ``ids``, take references on its pages into ``slot``'s
        table, and return the cached length (capped at ``len(ids) - 1``
        so the last prompt token always runs through the model — its
        logits seed the first sampled token). ``cap_last=False`` lifts
        that cap for the disaggregated handoff seat: the prefill worker
        already sampled the first token, so the decode worker may share
        the FULL prompt and never dispatch a prefill at all.

        Idempotent under the batcher's dispatch retry: any holdings a
        FAILED earlier admission attempt left in this slot (shared refs,
        stranded COW copies) are released first — without that, a
        transient prefill fault would double-ref the cached pages, and
        pages nobody holds could never return to the free list."""
        for pi in range(self.max_pages):
            pid = int(self.tables[slot, pi])
            if pid != NULL_PAGE:
                self.pool.unref(pid)
        self.tables[slot] = NULL_PAGE
        self.host_len[slot] = 0
        self.prefix_queries += 1
        self.prompt_tokens += len(ids)
        if not self.prefix_cache:
            return 0
        pages, matched = self.radix.match(ids, salt=salt)
        cached = min(matched, len(ids) - (1 if cap_last else 0))
        npages = self.pages_for(cached)
        for i in range(npages):
            self.pool.ref(pages[i])
            self.tables[slot, i] = pages[i]
        self.host_len[slot] = cached
        if cached > 0:
            self.prefix_hits += 1
            self.cached_tokens += cached
        return cached

    def peek_prefix(self, ids, cap_last: bool = True,
                    salt: str = "") -> int:
        """Read-only admission probe: the cached-prefix length
        ``match_prefix`` would resolve for ``ids``, WITHOUT taking page
        references, touching slot state, or counting a query — the
        mixed-dispatch batcher's lane-eligibility check (a one-shot-
        sized miss takes the serial one-shot path; everything else
        rides the lane)."""
        if not self.prefix_cache:
            return 0
        _, matched = self.radix.match(ids, salt=salt)
        return min(matched, len(ids) - (1 if cap_last else 0))

    def ensure_writable(self, slot: int, from_pos: int, to_pos: int) -> list:
        """Make rows ``[from_pos, to_pos)`` of ``slot`` writable: allocate
        missing pages, and for shared pages (refcount > 1) allocate a
        fresh page, record a (src, dst) copy-on-write pair for the engine
        to execute on device, and swap the slot's reference. Idempotent —
        already-exclusive pages are untouched. Clamped to the paged
        window. Raises PagePoolExhausted when the pool is truly dry."""
        to_pos = min(int(to_pos), self.max_pages * self.page_len)
        from_pos = max(int(from_pos), 0)
        cows = []
        if to_pos <= from_pos:
            return cows
        first = from_pos // self.page_len
        last = -(-to_pos // self.page_len)  # exclusive
        for pi in range(first, last):
            pid = int(self.tables[slot, pi])
            if pid == NULL_PAGE:
                self.tables[slot, pi] = self._alloc()
            elif self.pool.refs[pid] > 1:
                fresh = self._alloc()
                cows.append((pid, fresh))
                self.tables[slot, pi] = fresh
                self.pool.unref(pid)
                self.cow_copies += 1
        return cows

    # ---- page transport (prefill/decode disaggregation) -------------------

    def acquire_prefix(self, ids, salt: str = "") -> tuple:
        """Export pin: radix-match ``ids`` (within ``salt``'s domain) and
        take a TRANSIENT reference on every matched page so eviction (and
        any COW planning) cannot touch them while the transport
        serializes their bytes. Returns (page_ids, matched_tokens); the
        caller MUST ``release_pages`` the returned pages when done — the
        pin is a holder like any other."""
        if not self.prefix_cache:
            return [], 0
        pages, matched = self.radix.match(ids, salt=salt)
        npages = self.pages_for(matched)
        held = []
        for i in range(npages):
            self.pool.ref(pages[i])
            held.append(int(pages[i]))
        return held, matched

    def release_pages(self, pids) -> None:
        """Drop the transient references ``acquire_prefix`` (or a failed
        import) holds. Double drops raise — the pool's own discipline."""
        for pid in pids:
            self.pool.unref(int(pid))

    def alloc_import(self, n: int) -> list:
        """Allocate ``n`` pages for a transport import (refcount 1 held
        by the importer). All-or-nothing: on exhaustion every page of
        this batch is released before the raise, so a failed import can
        never leak pool capacity."""
        pids = []
        try:
            for _ in range(n):
                pids.append(self._alloc())
        except PagePoolExhausted:
            self.release_pages(pids)
            raise
        return pids

    def finish_import(self, ids, chunk_pids: dict, salt: str = "") -> int:
        """Graft written import pages into ``salt``'s radix domain and
        drop the importer's references: created nodes end held by the
        cache alone (refcount 1, evictable — exactly a registered
        prompt's state); duplicate chunks' pages free immediately.
        Returns nodes created."""
        created, _ = self.radix.adopt(ids, chunk_pids, salt=salt)
        self.release_pages(chunk_pids.values())
        return created

    def register_prompt(self, slot: int, ids, salt: str = "") -> None:
        """Insert a freshly prefilled prompt's pages into ``salt``'s
        radix domain (post-prefill: the pages hold final bytes; the
        slot's decode writes land past the prompt and COW first)."""
        if self.prefix_cache:
            self.radix.insert(ids, lambda i: int(self.tables[slot, i]),
                              salt=salt)

    def quant_flags(self) -> np.ndarray:
        """Per-page ``hot_bf16`` policy flags for the device
        (``page_quant``): 1 = read this page as int8 (cold — exactly one
        holder), 0 = read at full precision (hot — radix-shared prefixes
        and fork pages, anything with more than one holder; also free
        pages, which nothing reads). Recomputed from live refcounts
        before every dispatch (engine._sync_tables), so a page flips
        hot<->cold as sharing changes — both representations are always
        written, so the flip is metadata-only."""
        return (self.pool.refs == 1).astype(np.int32)

    def advance(self, slot_counts: np.ndarray) -> None:
        """Mirror device length advancement after a dispatch (counts per
        slot, 0 for inactive)."""
        self.host_len += np.asarray(slot_counts, np.int64)

    def set_len(self, slot: int, n: int) -> None:
        self.host_len[slot] = int(n)

    def free_slot(self, slot: int) -> None:
        """Release every page reference the slot holds (pages shared
        with the radix cache or other slots survive; exclusive ones
        return to the free list) and clear its table row."""
        for pi in range(self.max_pages):
            pid = int(self.tables[slot, pi])
            if pid != NULL_PAGE:
                self.pool.unref(pid)
        self.tables[slot] = NULL_PAGE
        self.host_len[slot] = 0
        self.priced[slot] = 0

    # ---- observability ----------------------------------------------------

    def stats(self) -> dict:
        """Pool occupancy + prefix-cache effectiveness (merged into
        ``batcher.stats()`` -> ``/statz`` and the bench JSON)."""
        total = self.pool.usable_pages
        live = self.pool.live_count
        return {
            "kv_layout": "paged",
            "kv_page_len": self.page_len,
            "kv_pages_total": total,
            "kv_pages_free": self.pool.free_count,
            "kv_pages_live": live,
            "kv_pool_utilization": round(live / max(total, 1), 4),
            "kv_pages_shared": self.pool.shared_count,
            "prefix_queries": self.prefix_queries,
            "prefix_hits": self.prefix_hits,
            "prefix_hit_rate": (
                round(self.cached_tokens / self.prompt_tokens, 4)
                if self.prompt_tokens else None),
            "prefix_cached_tokens": self.cached_tokens,
            "cow_copies": self.cow_copies,
            "radix_evictions": self.radix.evictions,
            # hot_bf16 policy mix over LIVE pages (cold = read as int8);
            # consumers that know the row byte widths (bench_decode's
            # kv_bytes_per_token) weight their accounting with this
            "kv_pages_quant": int(np.sum(self.pool.refs[1:] == 1)),
        }


# --------------------------------------------------------------------------- #
# dp-sharded host allocator
# --------------------------------------------------------------------------- #


class _PoolAggregate:
    """Read-only pool view summed over a ShardedPagedKV's shard pools —
    the surface ``batcher.refresh_gauges`` / bench / tests consume.
    ``refs`` concatenates the shard pools' refcount arrays in shard
    order, so it is indexed by GLOBAL page id (a copy: mutate the shard
    pools, never this)."""

    def __init__(self, owner: "ShardedPagedKV"):
        self._owner = owner
        self.num_pages = owner.num_pages

    @property
    def usable_pages(self) -> int:
        return sum(sh.pool.usable_pages for sh in self._owner.shards)

    @property
    def free_count(self) -> int:
        return sum(sh.pool.free_count for sh in self._owner.shards)

    @property
    def live_count(self) -> int:
        return sum(sh.pool.live_count for sh in self._owner.shards)

    @property
    def shared_count(self) -> int:
        return sum(sh.pool.shared_count for sh in self._owner.shards)

    @property
    def refs(self) -> np.ndarray:
        return np.concatenate([sh.pool.refs for sh in self._owner.shards])


class _ShardedRadix:
    """The slim radix surface external callers touch (page_transport's
    ``plan_adopt``, serve's drain-time ``cached_prefixes``, tests'
    ``match``), dispatched over per-shard tries. An import is planned and
    landed on ONE shard — ``plan_adopt`` records the chosen shard so the
    owner's ``alloc_import``/``finish_import`` land the pages there —
    picked as the shard already caching the most of the prefix (fewest
    missing chunks), free pages breaking ties."""

    def __init__(self, owner: "ShardedPagedKV"):
        self._owner = owner

    @property
    def evictions(self) -> int:
        return sum(sh.radix.evictions for sh in self._owner.shards)

    def match(self, ids, salt: str = "") -> tuple:
        """Longest cached prefix across every shard's trie, page ids
        GLOBAL. Ties go to the lowest shard (deterministic)."""
        best_pages, best_matched = [], 0
        for s, sh in enumerate(self._owner.shards):
            pages, matched = sh.radix.match(ids, salt=salt)
            if matched > best_matched:
                base = s * self._owner.pages_per_shard
                best_pages = [p + base for p in pages]
                best_matched = matched
        return best_pages, best_matched

    def plan_adopt(self, ids, salt: str = "") -> list:
        o = self._owner
        best, best_key = 0, None
        for s, sh in enumerate(o.shards):
            missing = len(sh.radix.plan_adopt(ids, salt=salt))
            key = (missing, -sh.pool.free_count, s)
            if best_key is None or key < best_key:
                best, best_key = s, key
        o._import_shard = best
        return o.shards[best].radix.plan_adopt(ids, salt=salt)

    def cached_prefixes(self, limit: int = 4) -> list:
        """Hottest cached prefixes across shards (per-shard LRU clocks
        are independent; round-robin merge keeps every shard's hottest
        represented)."""
        per = [sh.radix.cached_prefixes(limit) for sh in self._owner.shards]
        out: list = []
        i = 0
        while len(out) < max(0, limit) and any(per):
            for entries in per:
                if i < len(entries) and len(out) < limit:
                    out.append(entries[i])
            i += 1
            if all(i >= len(entries) for entries in per):
                break
        return out


class ShardedPagedKV:
    """Host-side page manager for a dp-sharded engine: ``dp_size``
    independent ``PagedKV`` allocators, one per dp shard, behind the
    global-slot / global-page-id surface the engine and batcher already
    speak.

    Layout contract (mirrors ``cache_pspecs(dp=...)``): global slot
    ``i`` lives on shard ``i // slots_per_shard``; shard ``s`` owns pool
    pages ``[s * pages_per_shard, (s+1) * pages_per_shard)`` and page
    ``s * pages_per_shard`` is that shard's NULL page (the reserved
    scribble target — so a slot's table NEVER references a page outside
    its own shard, and the jitted dispatch needs zero cross-shard
    traffic to resolve any table entry). ``tables`` materializes the
    global [slots, max_pages] int32 view with shard-local NULLs mapped
    to the owning shard's null page. ``host_len``/``priced`` are master
    numpy arrays whose per-shard slices are rewired INTO the shard
    allocators as views, so in-place writes on either side stay
    coherent.

    Prefix sharing is per shard (each shard's radix trie only ever
    references its own pages); cross-shard reuse happens by page
    MIGRATION (engine.migrate_slot / the batcher's rebalance planner),
    never by a table pointing across the dp axis."""

    def __init__(self, dp_size: int, slots: int, page_len: int,
                 max_pages: int, num_pages: int,
                 prefix_cache: bool = True):
        dp_size = int(dp_size)
        slots = int(slots)
        num_pages = int(num_pages)
        if dp_size < 1:
            raise ValueError("dp_size must be >= 1")
        if slots % dp_size:
            raise ValueError(
                f"slots ({slots}) must divide evenly over dp_size "
                f"({dp_size}) — each shard serves slots/dp slots")
        if num_pages % dp_size:
            raise ValueError(
                f"kv_num_pages ({num_pages}) must divide evenly over "
                f"dp_size ({dp_size}) — the pool page axis shards over "
                "'dp'")
        if num_pages // dp_size < 2:
            raise ValueError(
                "kv_num_pages must give every dp shard >= 2 pages "
                "(page 0 of each shard is its reserved NULL page)")
        self.dp_size = dp_size
        self.slots = slots
        self.slots_per_shard = slots // dp_size
        self.page_len = int(page_len)
        self.max_pages = int(max_pages)
        self.num_pages = num_pages
        self.pages_per_shard = num_pages // dp_size
        self.prefix_cache = bool(prefix_cache)
        self.shards = [
            PagedKV(self.slots_per_shard, self.page_len, self.max_pages,
                    self.pages_per_shard, prefix_cache=prefix_cache)
            for _ in range(dp_size)
        ]
        self.radix = _ShardedRadix(self)
        self.pool = _PoolAggregate(self)
        self._import_shard = None
        self.reset()

    # ---- shard/global coordinate helpers ----------------------------------

    def shard_of(self, slot: int) -> int:
        return int(slot) // self.slots_per_shard

    def local_slot(self, slot: int) -> int:
        return int(slot) % self.slots_per_shard

    def _shard_base(self, s: int) -> int:
        return s * self.pages_per_shard

    def reset(self) -> None:
        for sh in self.shards:
            sh.reset()
        # master slot-state arrays; shard allocators hold slice VIEWS so
        # their in-place writes (free_slot, match_prefix, set_len) land
        # in the master the engine/batcher read
        self.host_len = np.zeros(self.slots, np.int64)
        self.priced = np.zeros(self.slots, np.int64)
        spb = self.slots_per_shard
        for s, sh in enumerate(self.shards):
            sh.host_len = self.host_len[s * spb:(s + 1) * spb]
            sh.priced = self.priced[s * spb:(s + 1) * spb]
        self._import_shard = None

    # ---- global table view -------------------------------------------------

    @property
    def tables(self) -> np.ndarray:
        """Global [slots, max_pages] block tables with GLOBAL page ids:
        shard s's local entries offset by its page base, so its local
        NULL (0) becomes page ``s * pages_per_shard`` — exactly that
        shard's reserved null page under the dp-sharded pool layout.
        Recomputed per access (a copy: write through the shard
        allocators, never this view)."""
        return np.vstack([sh.tables + self._shard_base(s)
                          for s, sh in enumerate(self.shards)])

    # ---- pricing / admission ----------------------------------------------

    def pages_for(self, tokens: int) -> int:
        return self.shards[0].pages_for(tokens)

    @property
    def usable_pages(self) -> int:
        """Admission ceiling: the most pages ONE slot can ever hold. A
        slot's pages all live on its own shard, so this is a single
        shard's capacity — a request needing more can never fit, however
        empty the other shards are. (Aggregate capacity is
        ``pool.usable_pages``.)"""
        return self.pages_per_shard - 1

    def available_pages(self) -> int:
        return sum(sh.available_pages() for sh in self.shards)

    def can_admit(self, need: int, slot: int = None) -> bool:
        """Whether ``need`` pages are claimable — on ``slot``'s own shard
        when a slot is named (admission targets a specific seat), on ANY
        shard otherwise."""
        if slot is not None:
            return self.shards[self.shard_of(slot)].can_admit(need)
        return any(sh.can_admit(need) for sh in self.shards)

    # ---- slot lifecycle (global-slot delegation) --------------------------

    def match_prefix(self, slot: int, ids, cap_last: bool = True,
                     salt: str = "") -> int:
        return self.shards[self.shard_of(slot)].match_prefix(
            self.local_slot(slot), ids, cap_last=cap_last, salt=salt)

    def ensure_writable(self, slot: int, from_pos: int,
                        to_pos: int) -> list:
        s = self.shard_of(slot)
        base = self._shard_base(s)
        return [(src + base, dst + base) for src, dst in
                self.shards[s].ensure_writable(self.local_slot(slot),
                                               from_pos, to_pos)]

    def peek_prefix(self, ids, cap_last: bool = True, salt: str = "",
                    shard: int = 0) -> int:
        """Read-only probe against ONE shard's radix domain (prefix
        domains are per shard, so the caller names the shard the slot
        would seat on)."""
        return self.shards[shard].peek_prefix(ids, cap_last=cap_last,
                                              salt=salt)

    def register_prompt(self, slot: int, ids, salt: str = "") -> None:
        self.shards[self.shard_of(slot)].register_prompt(
            self.local_slot(slot), ids, salt=salt)

    def advance(self, slot_counts: np.ndarray) -> None:
        self.host_len += np.asarray(slot_counts, np.int64)

    def set_len(self, slot: int, n: int) -> None:
        self.host_len[slot] = int(n)

    def free_slot(self, slot: int) -> None:
        self.shards[self.shard_of(slot)].free_slot(self.local_slot(slot))

    def quant_flags(self) -> np.ndarray:
        """Global per-page flags, shard-major — the device
        ``page_quant``'s P('dp') layout."""
        return np.concatenate([sh.quant_flags() for sh in self.shards])

    # ---- page transport (global page ids) ---------------------------------

    def acquire_prefix(self, ids, salt: str = "") -> tuple:
        """Export pin against the shard caching the longest prefix of
        ``ids``; returns GLOBAL page ids."""
        if not self.prefix_cache:
            return [], 0
        best_s, best_matched = None, 0
        for s, sh in enumerate(self.shards):
            _, matched = sh.radix.match(ids, salt=salt)
            if matched > best_matched:
                best_s, best_matched = s, matched
        if best_s is None:
            # still counts as a query on shard 0 (the vanilla manager's
            # acquire path never touches counters; neither does this)
            return [], 0
        held, matched = self.shards[best_s].acquire_prefix(ids, salt=salt)
        base = self._shard_base(best_s)
        return [pid + base for pid in held], matched

    def release_pages(self, pids) -> None:
        pps = self.pages_per_shard
        for pid in pids:
            pid = int(pid)
            self.shards[pid // pps].pool.unref(pid % pps)

    def alloc_import(self, n: int) -> list:
        """Allocate ``n`` import pages on the shard ``radix.plan_adopt``
        chose (falling back to the freest shard when no plan ran);
        returns GLOBAL page ids. All-or-nothing like the vanilla path."""
        s = self._import_shard
        if s is None:
            s = max(range(self.dp_size),
                    key=lambda i: (self.shards[i].pool.free_count, -i))
            self._import_shard = s
        base = self._shard_base(s)
        return [pid + base for pid in self.shards[s].alloc_import(n)]

    def finish_import(self, ids, chunk_pids: dict, salt: str = "") -> int:
        """Graft import pages (GLOBAL ids, on the planned shard) into
        that shard's radix; clears the sticky import-shard choice."""
        s = self._import_shard
        if s is None and chunk_pids:
            s = next(iter(chunk_pids.values())) // self.pages_per_shard
        self._import_shard = None
        if s is None:
            return 0
        base = self._shard_base(s)
        local = {i: pid - base for i, pid in chunk_pids.items()}
        return self.shards[s].finish_import(ids, local, salt=salt)

    # ---- observability ----------------------------------------------------

    def shard_occupancy(self) -> list:
        """Occupied slots per shard (host_len > 0) — the rebalance
        planner's input and the ``picotron_shard_occupancy`` gauge."""
        spb = self.slots_per_shard
        return [int(np.count_nonzero(
            self.host_len[s * spb:(s + 1) * spb] > 0))
            for s in range(self.dp_size)]

    def stats(self) -> dict:
        total = self.pool.usable_pages
        live = self.pool.live_count
        agg = {
            "kv_layout": "paged",
            "kv_page_len": self.page_len,
            "kv_pages_total": total,
            "kv_pages_free": self.pool.free_count,
            "kv_pages_live": live,
            "kv_pool_utilization": round(live / max(total, 1), 4),
            "kv_pages_shared": self.pool.shared_count,
            "prefix_queries": sum(sh.prefix_queries for sh in self.shards),
            "prefix_hits": sum(sh.prefix_hits for sh in self.shards),
            "cow_copies": sum(sh.cow_copies for sh in self.shards),
            "radix_evictions": self.radix.evictions,
            "kv_pages_quant": sum(
                int(np.sum(sh.pool.refs[1:] == 1)) for sh in self.shards),
            "dp_size": self.dp_size,
            "kv_shard_pages_live": [sh.pool.live_count
                                    for sh in self.shards],
            "shard_occupancy": self.shard_occupancy(),
        }
        prompt = sum(sh.prompt_tokens for sh in self.shards)
        cached = sum(sh.cached_tokens for sh in self.shards)
        agg["prefix_hit_rate"] = (round(cached / prompt, 4)
                                  if prompt else None)
        agg["prefix_cached_tokens"] = cached
        return agg
