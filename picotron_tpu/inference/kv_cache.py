"""Preallocated slot-based KV cache + the masked dot-product decode kernel.

The training stack has no notion of a past: ``models/llama.py`` recomputes
every key/value each step. Serving needs the opposite — each generated token
must attend over all previous keys without recomputing them — so the cache
preallocates the whole attention past once and every decode step writes one
row per sequence:

- ``k``/``v``: ``[num_layers, slots, max_seq_len, n_kv_heads, head_dim]``.
  The layer axis leads (rather than the naive ``[batch, layers, ...]``
  ordering) so the decode step's ``lax.scan`` over the stacked layer axis
  consumes the cache exactly the way it consumes the stacked params; within
  a layer a block is ``[B, T, H, D]`` — the layout ``ops/attention.py``
  already uses. Heads are the COMPACT GQA count (``num_key_value_heads``,
  never repeated): repetition happens inside ``decode_attention`` via a
  grouped einsum, so GQA models pay ``Hkv/Hq`` of the naive cache bytes.
- ``lengths``: ``[slots]`` int32 — each sequence's write index (= tokens
  currently parked). Slot ``b``'s visible keys are ``t < lengths[b]``; a
  freed slot has ``lengths == 0`` and its stale rows are unreachable, which
  is what makes slot recycling (inference/batcher.py) a 1-element write.
- int8 mode (``inference.kv_cache_dtype: "int8"``): ``k``/``v`` store
  absmax-quantized int8 rows and the cache gains ``k_scale``/``v_scale``
  ``[num_layers, slots, max_seq_len, n_kv_heads]`` fp32 tensors — one scale
  per written row per kv head, so quantization error never crosses a head
  or a position. Quantization happens on write (``cache_write`` /
  prefill), dequantization inside ``attend`` right before the fp32-softmax
  attention. Cache bytes ≈ (1 + 4/head_dim) per element vs 2 for bf16 —
  ~53% at head_dim 64, i.e. ~2x the slots or context at the same HBM.

Sharding: the head axis shards over 'tp' — the same split as the wk/wv
columns that produce it — so a TP-sharded checkpoint decodes with zero
resharding; the scale tensors shard their (trailing) head axis the same
way; everything else is replicated (``cache_pspecs``). Unquantized dtype
follows the model's param dtype (bf16 on the production configs; fp32 tiny
CPU models stay exact against the ``forward_logits`` oracle).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from picotron_tpu.config import ModelConfig
from picotron_tpu.ops.attention import NEG_INF

# int8 symmetric range; scales are stored in fp32 so dequantization is one
# multiply with no double-rounding
INT8_MAX = 127.0
SCALE_DTYPE = jnp.float32


def cache_pspecs(quantized: bool = False, dp: int = 1) -> dict:
    """PartitionSpecs of the cache pytree: K/V head axis over 'tp', and —
    on a dp-sharded serving mesh (``dp > 1``) — the slot axis over 'dp',
    so each dp shard owns ``slots / dp`` contiguous slots of cache plus
    their length rows. ``dp == 1`` keeps the historical tp-only specs
    byte-identical. int8 caches add per-row scale tensors whose trailing
    head axis shards over 'tp' alongside the K/V heads they scale."""
    slot_ax = "dp" if dp > 1 else None
    kv = P(None, slot_ax, None, "tp", None)
    specs = {"k": kv, "v": kv,
             "lengths": P(slot_ax) if dp > 1 else P()}
    if quantized:
        scale = P(None, slot_ax, None, "tp")
        specs["k_scale"] = scale
        specs["v_scale"] = scale
    return specs


def init_cache(m: ModelConfig, slots: int, max_seq_len: int,
               dtype=None, quantized: bool = False) -> dict:
    """Zeroed global-shape cache for ``slots`` concurrent sequences. Jit
    with out_shardings (engine.init_cache) to materialize each device's
    shard directly."""
    shape = (m.num_hidden_layers, slots, max_seq_len,
             m.num_key_value_heads, m.head_dim)
    if quantized:
        cache = {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(shape[:-1], SCALE_DTYPE),
            "v_scale": jnp.zeros(shape[:-1], SCALE_DTYPE),
        }
    else:
        dt = jnp.dtype(dtype if dtype is not None else m.dtype)
        cache = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
    cache["lengths"] = jnp.zeros((slots,), jnp.int32)
    return cache


def cache_bytes(cache: dict) -> int:
    """Total bytes the cache pytree occupies (K/V + scales + lengths) —
    the HBM-budget metric the int8 mode halves."""
    return sum(a.size * a.dtype.itemsize for a in jax.tree.leaves(cache))


# --------------------------------------------------------------------------- #
# int8 quantization
# --------------------------------------------------------------------------- #


def quantize_kv(x: jnp.ndarray) -> tuple:
    """Absmax-quantize rows of ``x`` [..., head_dim] to int8: one fp32
    scale per leading index (= per written row per kv head). A zero row
    quantizes to zeros with scale 0 — dequantization is exact there."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = amax / INT8_MAX
    q = jnp.round(xf / jnp.maximum(scale, 1e-12)[..., None])
    return jnp.clip(q, -INT8_MAX, INT8_MAX).astype(jnp.int8), scale


def dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray, dtype) -> jnp.ndarray:
    """Inverse of ``quantize_kv``: [..., D] int8 * [...] scale -> dtype."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def quantized(cache: dict) -> bool:
    """Whether a cache pytree (full or per-layer) stores int8 K/V."""
    return "k_scale" in cache


# --------------------------------------------------------------------------- #
# per-layer cache ops (run inside the engine's layer scan / shard_map)
# --------------------------------------------------------------------------- #


def cache_write(layer_cache: dict, k_new: jnp.ndarray, v_new: jnp.ndarray,
                pos: jnp.ndarray) -> dict:
    """Write fresh K/V rows into one layer's cache block and return the
    updated block. Three shapes of write:

    - decode (``S == 1``): ``k_new``/``v_new`` [B, 1, H, D] with ``pos``
      [B] — every slot writes one row at its own position (a per-row
      scatter; free slots write their invisible row 0);
    - chunked prefill (``S > 1``, ``B == 1``): [1, S, H, D] with ``pos``
      [1] — one slot writes a contiguous block of rows starting at
      ``pos[0]``;
    - speculative verify (``S > 1``, ``B > 1``): [B, S, H, D] with ``pos``
      [B] — EVERY slot writes S contiguous rows starting at its own
      position (engine._verify_impl's optimistic draft write). Rows past
      the cache window drop (jax scatter out-of-bounds semantics — no
      clamping onto earlier rows), and rows past the post-acceptance
      length are stale: the length pointer is the rewind, ``attend``'s
      mask makes them unreachable (tests/test_speculative.py pins that a
      rejected draft leaves attention output identical to never having
      written it).

    int8 caches quantize on write; the scale rows land at the same
    positions in ``k_scale``/``v_scale``.

    RAGGED verify (the per-slot spec_len controller): a ``draft_valid``
    [B] int32 entry in ``layer_cache`` (spliced per dispatch by
    engine._verify_impl, never a stored leaf) caps each slot's write at
    its own count of REAL fed tokens — rows at or past it are redirected
    out of the window and DROP under jax's out-of-bounds scatter
    semantics, so a short-drafting slot never parks another slot's pad
    junk. Only the batched scatter honors it (the verify shape); the
    B == 1 dynamic-slice branch writes its whole block as before (a
    one-slot verify's pad rows land beyond the post-acceptance length,
    stale and unreachable — the pre-ragged contract).

    Paged caches (``inference.kv_layout: "paged"`` — the per-layer dict
    carries ``block_tables``) route to the page-indirect scatter
    (inference/paged_kv.py): same three write shapes, rows land in pool
    pages instead of a contiguous strip (ragged rows hit the NULL page).
    """
    if "block_tables" in layer_cache:
        from picotron_tpu.inference import paged_kv

        return paged_kv.cache_write(layer_cache, k_new, v_new, pos)
    out = dict(layer_cache)
    valid = out.pop("draft_valid", None)
    B, S = k_new.shape[0], k_new.shape[1]
    T = layer_cache["k"].shape[1]

    def store(name, sname, new):
        if quantized(layer_cache):
            vals, scales = quantize_kv(new)
        else:
            vals, scales = new.astype(layer_cache[name].dtype), None
        if S == 1:
            rows = jnp.arange(B)
            out[name] = layer_cache[name].at[rows, pos].set(vals[:, 0])
            if scales is not None:
                out[sname] = layer_cache[sname].at[rows, pos].set(
                    scales[:, 0].astype(SCALE_DTYPE))
        elif B == 1:
            start = jnp.asarray(pos[0], jnp.int32)
            out[name] = lax.dynamic_update_slice(
                layer_cache[name], vals, (0, start, 0, 0))
            if scales is not None:
                out[sname] = lax.dynamic_update_slice(
                    layer_cache[sname], scales.astype(SCALE_DTYPE),
                    (0, start, 0))
        else:
            rows = pos[:, None] + jnp.arange(S, dtype=pos.dtype)[None, :]
            if valid is not None:
                # ragged mask: rows past the slot's own real-token count
                # go out of bounds, where the scatter drops them
                cols = jnp.arange(S, dtype=jnp.int32)[None, :]
                rows = jnp.where(cols < valid[:, None], rows, T)
            bidx = jnp.arange(B)[:, None]
            out[name] = layer_cache[name].at[bidx, rows].set(vals)
            if scales is not None:
                out[sname] = layer_cache[sname].at[bidx, rows].set(
                    scales.astype(SCALE_DTYPE))

    store("k", "k_scale", k_new)
    store("v", "v_scale", v_new)
    return out


def attend(q: jnp.ndarray, layer_cache: dict, lengths: jnp.ndarray,
           scale: float, impl: str = "dense") -> jnp.ndarray:
    """Masked attention of S fresh queries against one layer's cache block.

    ``impl`` picks the kernel (config ``inference.attend_impl``):

    - "dense" (default): ``decode_attention`` over the whole cache window,
      int8 storage first dequantized to a whole-block fp32 copy (the
      bit-pinned reference path);
    - "flash": the Pallas flash-decode kernel
      (ops/pallas/decode_attention.py) — KV blocks are read only up to
      each slot's live length with DOUBLE-BUFFERED DMA (block j+1's copy
      commits while block j's dots run), int8 bytes + per-row scales
      travel to the kernel as stored and dequantize in registers: no
      whole-cache fp32 materialization ever exists on this path. Wide
      chunked-prefill query windows split over a q-block grid axis
      (flash_attention's causal block-skip bounds each tile's walk).
      Runs in interpret mode off TPU; allclose-pinned against dense
      (tests/test_decode_kernel.py).

    Paged caches (the per-layer dict carries ``block_tables``) route to
    the page-indirect attends (inference/paged_kv.py): dense gathers the
    slots' pages into a contiguous window and runs the same masked
    einsum; flash walks the block table page by page in the kernel.
    """
    if "block_tables" in layer_cache:
        from picotron_tpu.inference import paged_kv

        return paged_kv.attend(q, layer_cache, lengths, scale, impl)
    if impl == "flash":
        from picotron_tpu.ops.pallas.decode_attention import (
            flash_decode_attention,
        )
        from picotron_tpu.utils import on_tpu

        return flash_decode_attention(
            q, layer_cache["k"], layer_cache["v"], lengths, scale,
            k_scale=layer_cache.get("k_scale"),
            v_scale=layer_cache.get("v_scale"),
            interpret=not on_tpu())
    if impl != "dense":
        # a typo'd impl must not silently measure the wrong kernel
        raise ValueError(f"unknown attend impl {impl!r} (dense|flash)")
    if quantized(layer_cache):
        k = dequantize_kv(layer_cache["k"], layer_cache["k_scale"],
                          jnp.float32)
        v = dequantize_kv(layer_cache["v"], layer_cache["v_scale"],
                          jnp.float32)
    else:
        k, v = layer_cache["k"], layer_cache["v"]
    return decode_attention(q, k, v, lengths, scale)


def decode_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     lengths: jnp.ndarray, scale: float) -> jnp.ndarray:
    """Masked dot-product attention of S fresh queries against a cache block.

    q: [B, S, n_heads, D] — the new tokens, the LAST of which sits at global
    position ``lengths[b] - 1`` (its K/V are already written); k/v:
    [B, T, n_kv_heads, D] cache blocks; lengths: [B] int32 valid-key counts.
    GQA is handled natively by a grouped einsum over the compact kv heads —
    no repeat, no extra cache bytes. fp32 softmax with the same NEG_INF
    masking convention as ops/attention.py, output cast back to q.dtype.

    S == 1 is the autoregressive decode step; S > 1 is chunked continuation
    — prefill chunks (B == 1) or speculative verify batches (B > 1)
    attending over the already-written prefix plus themselves (each query i
    masks keys past its own position).
    """
    B, S, nh, D = q.shape
    T, nkv = k.shape[1], k.shape[2]
    g = nh // nkv
    qg = q.reshape(B, S, nkv, g, D)
    scores = jnp.einsum("bskgd,btkd->bkgst", qg, k,
                        preferred_element_type=jnp.float32) * scale
    # query s has global position lengths - S + s; key t visible iff t <= it
    pos_q = lengths[:, None] - S + jnp.arange(S)[None, :]  # [B, S]
    mask = jnp.arange(T)[None, None, :] <= pos_q[:, :, None]  # [B, S, T]
    scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bkgst,btkd->bskgd", p, v.astype(jnp.float32))
    return out.reshape(B, S, nh, D).astype(q.dtype)


# --------------------------------------------------------------------------- #
# whole-cache ops (host-facing, jitted by the engine)
# --------------------------------------------------------------------------- #


def insert_prefill(cache: dict, kv: dict, slot, length) -> dict:
    """Park a prefill's ``{"k","v"[,"k_scale","v_scale"]}:
    [L, 1, S_bucket, H, D(, )]`` blocks into ``slot`` and set its length
    (the engine's prefill already quantized the blocks for int8 caches).
    Rows past ``length`` (the bucket pad) are written but unreachable under
    the length mask. ``slot``/``length`` may be traced scalars — one
    compile per bucket size, not per slot."""
    slot = jnp.asarray(slot, jnp.int32)

    def put(name):
        dst, src = cache[name], kv[name].astype(cache[name].dtype)
        return lax.dynamic_update_slice(
            dst, src, (0, slot) + (0,) * (dst.ndim - 2))

    out = {name: put(name) for name in cache if name != "lengths"}
    out["lengths"] = cache["lengths"].at[slot].set(
        jnp.asarray(length, jnp.int32))
    return out


def release(cache: dict, slot) -> dict:
    """Free a slot: zero its length so no stale key is ever visible again.
    The K/V rows themselves stay — the next occupant overwrites what it
    needs and masks the rest."""
    return {**cache, "lengths": cache["lengths"].at[slot].set(0)}


def live_tokens(cache: dict) -> jax.Array:
    """Total tokens currently parked across slots (occupancy metric for
    the batcher/bench)."""
    return jnp.sum(cache["lengths"])
