"""Preallocated slot-based KV cache + the masked dot-product decode kernel.

The training stack has no notion of a past: ``models/llama.py`` recomputes
every key/value each step. Serving needs the opposite — each generated token
must attend over all previous keys without recomputing them — so the cache
preallocates the whole attention past once and every decode step writes one
row per sequence:

- ``k``/``v``: ``[num_layers, slots, max_seq_len, n_kv_heads, head_dim]``.
  The layer axis leads (rather than the naive ``[batch, layers, ...]``
  ordering) so the decode step's ``lax.scan`` over the stacked layer axis
  consumes the cache exactly the way it consumes the stacked params; within
  a layer a block is ``[B, T, H, D]`` — the layout ``ops/attention.py``
  already uses. Heads are the COMPACT GQA count (``num_key_value_heads``,
  never repeated): repetition happens inside ``decode_attention`` via a
  grouped einsum, so GQA models pay ``Hkv/Hq`` of the naive cache bytes.
- ``lengths``: ``[slots]`` int32 — each sequence's write index (= tokens
  currently parked). Slot ``b``'s visible keys are ``t < lengths[b]``; a
  freed slot has ``lengths == 0`` and its stale rows are unreachable, which
  is what makes slot recycling (inference/batcher.py) a 1-element write.

Sharding: the head axis shards over 'tp' — the same split as the wk/wv
columns that produce it — so a TP-sharded checkpoint decodes with zero
resharding; everything else is replicated (``cache_pspecs``). Dtype follows
the model's param dtype (bf16 on the production configs; fp32 tiny CPU
models stay exact against the ``forward_logits`` oracle).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from picotron_tpu.config import ModelConfig
from picotron_tpu.ops.attention import NEG_INF


def cache_pspecs() -> dict:
    """PartitionSpecs of the cache pytree: K/V head axis over 'tp', the
    rest replicated (slots could shard over 'dp' later; the engine serves
    a tp-only mesh today)."""
    kv = P(None, None, None, "tp", None)
    return {"k": kv, "v": kv, "lengths": P()}


def init_cache(m: ModelConfig, slots: int, max_seq_len: int,
               dtype=None) -> dict:
    """Zeroed global-shape cache for ``slots`` concurrent sequences. Jit
    with out_shardings (engine.init_cache) to materialize each device's
    shard directly."""
    dt = jnp.dtype(dtype if dtype is not None else m.dtype)
    shape = (m.num_hidden_layers, slots, max_seq_len,
             m.num_key_value_heads, m.head_dim)
    return {
        "k": jnp.zeros(shape, dt),
        "v": jnp.zeros(shape, dt),
        "lengths": jnp.zeros((slots,), jnp.int32),
    }


def decode_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     lengths: jnp.ndarray, scale: float) -> jnp.ndarray:
    """Masked dot-product attention of S fresh queries against a cache block.

    q: [B, S, n_heads, D] — the new tokens, the LAST of which sits at global
    position ``lengths[b] - 1`` (its K/V are already written); k/v:
    [B, T, n_kv_heads, D] cache blocks; lengths: [B] int32 valid-key counts.
    GQA is handled natively by a grouped einsum over the compact kv heads —
    no repeat, no extra cache bytes. fp32 softmax with the same NEG_INF
    masking convention as ops/attention.py, output cast back to q.dtype.

    S == 1 is the autoregressive decode step; S > 1 generalizes to chunked
    continuation (each query i masks keys past its own position).
    """
    B, S, nh, D = q.shape
    T, nkv = k.shape[1], k.shape[2]
    g = nh // nkv
    qg = q.reshape(B, S, nkv, g, D)
    scores = jnp.einsum("bskgd,btkd->bkgst", qg, k,
                        preferred_element_type=jnp.float32) * scale
    # query s has global position lengths - S + s; key t visible iff t <= it
    pos_q = lengths[:, None] - S + jnp.arange(S)[None, :]  # [B, S]
    mask = jnp.arange(T)[None, None, :] <= pos_q[:, :, None]  # [B, S, T]
    scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bkgst,btkd->bskgd", p, v.astype(jnp.float32))
    return out.reshape(B, S, nh, D).astype(q.dtype)


def insert_prefill(cache: dict, kv: dict, slot, length) -> dict:
    """Park a prefill's ``{"k","v"}: [L, 1, S_bucket, H, D]`` blocks into
    ``slot`` and set its length. Rows past ``length`` (the bucket pad) are
    written but unreachable under the length mask. ``slot``/``length`` may
    be traced scalars — one compile per bucket size, not per slot."""
    slot = jnp.asarray(slot, jnp.int32)

    def put(dst, src):
        return lax.dynamic_update_slice(dst, src, (0, slot, 0, 0, 0))

    return {
        "k": put(cache["k"], kv["k"].astype(cache["k"].dtype)),
        "v": put(cache["v"], kv["v"].astype(cache["v"].dtype)),
        "lengths": cache["lengths"].at[slot].set(
            jnp.asarray(length, jnp.int32)),
    }


def release(cache: dict, slot) -> dict:
    """Free a slot: zero its length so no stale key is ever visible again.
    The K/V rows themselves stay — the next occupant overwrites what it
    needs and masks the rest."""
    return {**cache, "lengths": cache["lengths"].at[slot].set(0)}


def live_tokens(cache: dict) -> jax.Array:
    """Total tokens currently parked across slots (occupancy metric for
    the batcher/bench)."""
    return jnp.sum(cache["lengths"])
