"""Multi-tenant serving: the tenant registry and the adapter pack.

One deployment, many products. A **tenant** is a named serving identity
carrying four things:

- an optional LoRA adapter (rank, seed or .npz weights) applied as an
  additive residual on the seven decoder-layer projections — batched
  with every other tenant's adapter in ONE dispatch by the segmented
  matmul (ops/pallas/lora_matmul.py);
- a **priority class** (higher sheds later): under queue or page
  pressure the scheduler sheds the lowest class first and the admission
  ladder seats higher classes first;
- **quotas** (in-flight token and KV-page budgets) priced through the
  batcher's existing ``commitment()`` / ``page_commitment`` ladder;
- **SLO targets** (TTFT / TPOT milliseconds) that steer chunked-prefill
  interleaving, the SpecController's per-slot spec_len, and router
  placement.

The **AdapterPack** is the device-side half: fixed-capacity stacked
``a [L, T, in, r]`` / ``b [L, T, r, out]`` arrays per projection leaf,
zero everywhere a slot is free. Slot 0 is the reserved NULL adapter
(A = B = 0) — base-only rows point at it and bypass exactly. Hot
add/remove writes one slot of the host master and bumps ``version``;
the engine re-places the pack on its mesh at the next dispatch, so
tenant churn never recompiles a program (shapes are capacity-static).

The **TenantRegistry** is the host-side half: name -> Tenant + pack
slot, loaded from config or a JSON manifest::

    {"tenants": [
        {"name": "acme", "priority": 2, "adapter_rank": 8,
         "adapter_seed": 7, "max_tokens": 4096, "max_pages": 256,
         "ttft_slo_ms": 300.0, "tpot_slo_ms": 50.0},
        {"name": "bulk", "priority": 0}
    ]}

and mutated at runtime via serve.py's ``/tenants`` admin endpoint.
Rank-0 tenants (no adapter) share slot 0 and consume no pack capacity.

Tenant identity also salts the KV reuse planes: the radix prefix cache
keys per-tenant subtrees (paged_kv.RadixCache) and the page-transport
chunk keys carry the tenant (page_transport), so identical prompts
under different tenants never share pages or handoff chunks — the
adapter changes every activation a cached page holds — while same-
tenant sharing still works cluster-wide.
"""

from __future__ import annotations

import dataclasses
import json
import threading

import jax.numpy as jnp
import numpy as np

from picotron_tpu.models import llama
from picotron_tpu.ops.pallas.lora_matmul import ADAPTER_DTYPE, NULL_ADAPTER

# The seven projection leaves an adapter modifies (the PR 13 dispatch
# seam; the LM head stays base-only — classic LoRA placement).
LORA_LEAVES = llama.QUANT_WEIGHT_LEAVES

# Default tenant identity for requests that name none: base model, no
# adapter, middle priority, no quotas, no SLOs.
BASE_TENANT = "base"

# Default random-init scale for seed-derived adapters (smoke/bench/test
# path): small enough that tiny test models keep coherent generations,
# large enough that tenants' outputs measurably differ.
DEFAULT_ADAPTER_SCALE = 0.05


def adapter_dims(m) -> dict:
    """Per-leaf ``(in_features, out_features)`` for the seven projection
    weights, from the model config (matches llama.init_params)."""
    H, I, D = m.hidden_size, m.intermediate_size, m.head_dim
    Hq, Hkv = m.num_attention_heads * D, m.num_key_value_heads * D
    return {
        "wq": (H, Hq), "wk": (H, Hkv), "wv": (H, Hkv), "wo": (Hq, H),
        "w_gate": (H, I), "w_up": (H, I), "w_down": (I, H),
    }


@dataclasses.dataclass
class Tenant:
    """One serving identity. ``priority``: higher holds admission longer
    under pressure (0 = best-effort, shed first). ``adapter_rank`` 0
    means base-only (null adapter, slot 0). Quotas are in-flight
    ceilings; None = unlimited. SLO targets are milliseconds; None =
    no target."""
    name: str
    priority: int = 1
    adapter_rank: int = 0
    adapter_seed: int = 0
    adapter_scale: float = DEFAULT_ADAPTER_SCALE
    adapter_npz: str | None = None
    max_tokens: int | None = None
    max_pages: int | None = None
    ttft_slo_ms: float | None = None
    tpot_slo_ms: float | None = None

    def __post_init__(self):
        if not self.name or "/" in self.name or '"' in self.name:
            raise ValueError(
                f"tenant name {self.name!r} must be non-empty and free of "
                f"'/' and '\"' (it labels metrics and salts cache keys)")
        if self.priority < 0:
            raise ValueError(
                f"tenant {self.name}: priority must be >= 0 "
                f"(0 = best-effort, shed first)")
        if self.adapter_rank < 0:
            raise ValueError(
                f"tenant {self.name}: adapter_rank must be >= 0 "
                f"(0 = base-only)")
        for f in ("max_tokens", "max_pages"):
            v = getattr(self, f)
            if v is not None and v < 1:
                raise ValueError(
                    f"tenant {self.name}: {f} must be >= 1 or absent")
        for f in ("ttft_slo_ms", "tpot_slo_ms"):
            v = getattr(self, f)
            if v is not None and v <= 0:
                raise ValueError(
                    f"tenant {self.name}: {f} must be > 0 ms or absent")

    @classmethod
    def from_dict(cls, d: dict) -> "Tenant":
        known = {f.name for f in dataclasses.fields(cls)}
        bad = set(d) - known
        if bad:
            raise ValueError(
                f"unknown tenant field(s) {sorted(bad)} for "
                f"{d.get('name', '?')!r} (known: {sorted(known)})")
        return cls(**d)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class AdapterPack:
    """Fixed-capacity stacked adapter storage for one model shape.

    ``slots`` total adapter slots (slot 0 reserved null), ``rank`` the
    capacity rank R: a tenant of rank r <= R occupies the first r
    columns of its slot, the rest stay zero — exact, since zero columns
    contribute nothing to the residual. Mutations write the host master
    and bump ``version``; ``device_leaves()`` lazily (re-)materializes
    the jnp arrays, so callers that cache by version re-place only
    after churn. Shapes never change after construction — hot
    add/remove never recompiles a serving program."""

    def __init__(self, m, *, slots: int = 8, rank: int = 16,
                 rows: int | None = None):
        if slots < 2:
            raise ValueError(
                f"adapter_slots must be >= 2 (slot 0 is the reserved "
                f"null adapter); got {slots}")
        if rank < 1:
            raise ValueError(f"adapter_rank capacity must be >= 1; "
                             f"got {rank}")
        self.slots, self.rank = int(slots), int(rank)
        self.rows = int(rows or m.num_hidden_layers)
        self.dims = adapter_dims(m)
        self._host = {
            name: (np.zeros((self.rows, self.slots, din, self.rank),
                            np.float32),
                   np.zeros((self.rows, self.slots, self.rank, dout),
                            np.float32))
            for name, (din, dout) in self.dims.items()
        }
        self.version = 0
        self._device = None
        self._device_version = -1
        self._lock = threading.Lock()

    # -- mutation (host master; device refresh is lazy) ----------------------

    def set_slot(self, slot: int, leaves: dict) -> None:
        """Install adapter weights into ``slot``. ``leaves`` maps leaf
        name -> (a [rows, in, r], b [rows, r, out]) with r <= capacity;
        missing leaves zero out (adapter doesn't touch them)."""
        self._check_slot(slot)
        with self._lock:
            for name, (ha, hb) in self._host.items():
                ha[:, slot] = 0.0
                hb[:, slot] = 0.0
                if name not in leaves:
                    continue
                a, b = leaves[name]
                a = np.asarray(a, np.float32)
                b = np.asarray(b, np.float32)
                din, dout = self.dims[name]
                r = a.shape[-1]
                if (a.shape != (self.rows, din, r)
                        or b.shape != (self.rows, r, dout)
                        or r > self.rank):
                    raise ValueError(
                        f"adapter leaf {name}: got a {a.shape} / b "
                        f"{b.shape}; want a [{self.rows}, {din}, r] / "
                        f"b [{self.rows}, r, {dout}] with r <= "
                        f"{self.rank}")
                ha[:, slot, :, :r] = a
                hb[:, slot, :r, :] = b
            self.version += 1

    def clear_slot(self, slot: int) -> None:
        """Zero a slot back to null (hot remove)."""
        self._check_slot(slot)
        with self._lock:
            for ha, hb in self._host.values():
                ha[:, slot] = 0.0
                hb[:, slot] = 0.0
            self.version += 1

    def random_leaves(self, rank: int, seed: int,
                      scale: float = DEFAULT_ADAPTER_SCALE) -> dict:
        """Seed-derived adapter weights (the smoke/bench/test path —
        deterministic per (rank, seed), visibly non-null)."""
        if not 1 <= rank <= self.rank:
            raise ValueError(
                f"adapter rank {rank} outside [1, capacity {self.rank}]")
        rng = np.random.default_rng(seed)
        out = {}
        for name, (din, dout) in self.dims.items():
            out[name] = (
                rng.normal(0.0, scale,
                           (self.rows, din, rank)).astype(np.float32),
                rng.normal(0.0, scale,
                           (self.rows, rank, dout)).astype(np.float32))
        return out

    def npz_leaves(self, path: str) -> dict:
        """Adapter weights from an .npz archive with ``{leaf}.a`` /
        ``{leaf}.b`` arrays (offline-trained adapters)."""
        with np.load(path) as z:
            out = {}
            for name in self.dims:
                ka, kb = f"{name}.a", f"{name}.b"
                if ka in z and kb in z:
                    out[name] = (z[ka], z[kb])
            if not out:
                raise ValueError(
                    f"adapter archive {path} has no '<leaf>.a'/'<leaf>.b' "
                    f"arrays for leaves {sorted(self.dims)}")
        return out

    # -- device side ---------------------------------------------------------

    def device_leaves(self, place=None) -> dict:
        """The pack as jnp arrays, ``{leaf: {"a": [L, T, in, R],
        "b": [L, T, R, out]}}`` — cached until the next mutation.
        ``place`` (optional) maps (leaf_name, side, host_array) -> device
        array so the engine can land shards straight on its mesh."""
        with self._lock:
            if self._device is not None \
                    and self._device_version == self.version:
                return self._device
            put = place or (lambda _n, _s, arr: jnp.asarray(
                arr, ADAPTER_DTYPE))
            self._device = {
                name: {"a": put(name, "a", ha), "b": put(name, "b", hb)}
                for name, (ha, hb) in self._host.items()
            }
            self._device_version = self.version
            return self._device

    def bytes_per_token(self) -> int:
        """Adapter bytes one decoded token streams for one adapter-bound
        row: each layer reads its [in, R] + [R, out] fp32 pair."""
        per_layer = sum((din + dout) * self.rank
                        for din, dout in self.dims.values())
        return self.rows * per_layer * np.dtype(np.float32).itemsize

    def _check_slot(self, slot: int) -> None:
        if not NULL_ADAPTER < slot < self.slots:
            raise ValueError(
                f"adapter slot {slot} outside (0, {self.slots}) — slot 0 "
                f"is the reserved null adapter")


class TenantRegistry:
    """name -> (Tenant, adapter slot), with hot add/remove.

    The registry owns slot assignment on its AdapterPack (rank-0 tenants
    share the null slot 0 and consume no capacity). The implicit
    ``base`` tenant always resolves — requests that name no tenant get
    it — unless the manifest defines its own ``base`` entry, which then
    governs (e.g. to give anonymous traffic a priority or quota)."""

    def __init__(self, pack: AdapterPack | None = None):
        self.pack = pack
        self._tenants: dict = {}
        self._slots: dict = {}
        self._lock = threading.Lock()
        self._base = Tenant(name=BASE_TENANT)

    # -- construction --------------------------------------------------------

    @classmethod
    def from_manifest(cls, path: str,
                      pack: AdapterPack | None = None) -> "TenantRegistry":
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        if not isinstance(doc, dict) or not isinstance(
                doc.get("tenants", None), list):
            raise ValueError(
                f"tenant manifest {path} must be a JSON object with a "
                f"'tenants' list")
        reg = cls(pack)
        for entry in doc["tenants"]:
            reg.add(Tenant.from_dict(entry))
        return reg

    # -- mutation ------------------------------------------------------------

    def add(self, tenant: Tenant) -> int:
        """Register a tenant (hot). Returns its adapter slot. Raises on
        duplicate names, missing pack, or a full pack."""
        with self._lock:
            if tenant.name in self._tenants:
                raise ValueError(f"tenant {tenant.name!r} already exists")
            slot = NULL_ADAPTER
            if tenant.adapter_rank > 0:
                if self.pack is None:
                    raise ValueError(
                        f"tenant {tenant.name!r} wants adapter rank "
                        f"{tenant.adapter_rank} but no adapter pack is "
                        f"configured (inference.tenancy.adapter_slots)")
                used = set(self._slots.values())
                free = [s for s in range(1, self.pack.slots)
                        if s not in used]
                if not free:
                    raise ValueError(
                        f"adapter pack full ({self.pack.slots - 1} "
                        f"slots); remove a tenant first")
                slot = free[0]
                if tenant.adapter_npz:
                    leaves = self.pack.npz_leaves(tenant.adapter_npz)
                else:
                    leaves = self.pack.random_leaves(
                        tenant.adapter_rank, tenant.adapter_seed,
                        tenant.adapter_scale)
                self.pack.set_slot(slot, leaves)
                self._slots[tenant.name] = slot
            self._tenants[tenant.name] = tenant
            return slot

    def remove(self, name: str) -> None:
        """Deregister (hot): the slot zeroes back to null, so in-flight
        rows still pointing at it degrade to base-model output rather
        than another tenant's adapter."""
        with self._lock:
            if name not in self._tenants:
                raise KeyError(f"no tenant {name!r}")
            del self._tenants[name]
            slot = self._slots.pop(name, None)
            if slot is not None and self.pack is not None:
                self.pack.clear_slot(slot)

    # -- lookup --------------------------------------------------------------

    def resolve(self, name: str | None) -> tuple:
        """(Tenant, adapter slot) for a request's tenant field; None or
        "" resolves to the base identity. KeyError on unknown names —
        serve.py turns that into a 4xx, never a silent base fallback
        (a typo'd tenant must not dodge its quota)."""
        name = name or BASE_TENANT
        with self._lock:
            if name in self._tenants:
                return (self._tenants[name],
                        self._slots.get(name, NULL_ADAPTER))
        if name == BASE_TENANT:
            return self._base, NULL_ADAPTER
        raise KeyError(f"unknown tenant {name!r}")

    def names(self) -> list:
        with self._lock:
            return sorted(self._tenants)

    def snapshot(self) -> list:
        """Admin-endpoint view: every tenant + its slot (base implied)."""
        with self._lock:
            return [{**t.to_dict(), "adapter_slot":
                     self._slots.get(n, NULL_ADAPTER)}
                    for n, t in sorted(self._tenants.items())]
