"""TPU-native inference: KV-cache decode, sampling, continuous batching.

The serving counterpart of the training stack — turns trained checkpoints
into a batched generation engine:

- ``kv_cache``: preallocated slot-based K/V cache (compact GQA heads, head
  axis tp-sharded; optional int8 storage with per-row absmax scales) + the
  masked dot-product decode kernel;
- ``paged_kv``: the paged layout (``inference.kv_layout: "paged"``) — a
  global pool of fixed-size KV pages behind per-slot block tables, with a
  host-side refcounting allocator, radix prefix sharing (identical prompt
  prefixes stored and prefilled once), and copy-on-write at fork points;
- ``sampling``: greedy / temperature / top-k / top-p as pure jittable
  functions with per-request parameter arrays — also the fused on-device
  epilogue (``inference.sample_on_device``) that keeps full-vocab logits
  from ever crossing to the host;
- ``engine``: jitted ``prefill`` / ``prefill_chunked`` / ``decode_step`` /
  ``decode_block`` programs under shard_map on a tp mesh, reusing the
  training ``decoder_layer`` (flash-capable prefill) with the
  incremental-decode hooks; ``decode_block`` fuses ``decode_block_len``
  steps with on-device EOS/budget stop state — one host sync per block;
- ``batcher``: continuous batching — admit/retire variable-length requests
  into the engine's fixed slots, consuming whole decode blocks (or
  draft-verify dispatches on a speculative engine);
- ``page_transport``: the prefill/decode disaggregation handoff — a
  prefilled request's KV leaves one replica as pool page bytes (+ radix
  chunk keys + the first sampled token) and lands in another's pool
  byte-exact, CRC-guarded and refcount-correct, so dedicated prefill
  workers feed decode workers whose batcher never spends a dispatch on
  a long prefill, and a replica can import a peer's cached prefix
  instead of recomputing it (docs/SERVING.md "Disaggregated
  prefill/decode");
- ``speculative``: the draft side of speculative decoding plus its
  policy loop — the ``Drafter`` interface, the model-free prompt-lookup
  ``NgramDrafter`` (incremental append-only suffix index, windowed match
  scan), the EAGLE-style ``LearnedDrafter`` (tiny head over the target's
  own last hidden state sharing the target's embedding/lm_head — the
  engine's ``return_hidden`` hook keeps that state on device), and the
  ``SpecController`` closed loop that reads the obs registry's live
  accept counters + dispatch latencies and sets ``spec_len`` per slot
  each round; ``engine.verify`` scores ``spec_len + 1`` positions per
  slot in one (per-slot RAGGED) dispatch and
  ``sampling.speculative_accept`` keeps the matching prefix (exact for
  greedy, rejection-sampled for stochastic) — one model pass per
  ACCEPTED RUN instead of per token.

Design notes and CLI usage: docs/INFERENCE.md.
"""

from picotron_tpu.inference.batcher import (  # noqa: F401
    ContinuousBatcher,
    GenerationResult,
    Request,
)
from picotron_tpu.inference.engine import (  # noqa: F401
    InferenceEngine,
    inference_config,
)
from picotron_tpu.inference.speculative import (  # noqa: F401
    Drafter,
    LearnedDrafter,
    NgramDrafter,
    SpecController,
    init_draft_head,
)
