"""Sampling over next-token logits: greedy, temperature, top-k, top-p.

Pure jittable functions over full-vocab logits ``[B, V]`` with PER-REQUEST
parameter arrays ``[B]`` — one compiled program serves a continuous batch
whose slots carry different settings (a slot's params change between steps
without recompiling, because they are array values, not trace constants).

Filter order follows the de-facto HF convention: temperature scaling first,
then top-k, then top-p on the rescaled distribution. ``temperature == 0``
means greedy (argmax) for that row; ``top_k <= 0`` and ``top_p >= 1``
disable their filters. Masked logits use the same large-negative fill as
ops/attention.py so fully-filtered rows stay finite.

``speculative_accept`` is the draft-acceptance rule for speculative
decoding (engine.verify): exact-match for greedy rows, rejection sampling
with residual-distribution resampling for stochastic rows — the emitted
stream is distributionally identical to drawing token-by-token from
``sample`` over the same filtered logits.

``sample`` is also the FUSED ON-DEVICE EPILOGUE
(``inference.sample_on_device``): the engine's prefill/chunked-prefill/
decode_step programs call it inside the jitted dispatch — the one
descending sort of ``filter_top_k_top_p`` plus the categorical draw —
so token ids, not ``[B, vocab]`` logits, are what crosses to the host.
Same function, same key, either side of the boundary: that is what makes
the epilogue seeded-identical to the host sampler by construction.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from picotron_tpu.ops.attention import NEG_INF


def greedy(logits: jnp.ndarray) -> jnp.ndarray:
    """Argmax decode: [B, V] -> [B] int32."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def sanitize_logits(logits: jnp.ndarray) -> jnp.ndarray:
    """Replace non-finite entries with the mask fill. The serving analogue
    of train_step's non-finite gate: a poisoned/overflowed dispatch must not
    push NaN through the categorical (whose draw would be garbage) and from
    there into the KV state — masked, the bad entries simply can never be
    selected. Identity on finite logits, so healthy decode is untouched."""
    return jnp.where(jnp.isfinite(logits), logits, NEG_INF)


def nonfinite_rows(logits: jnp.ndarray) -> jnp.ndarray:
    """[..., V] -> [...] bool: rows carrying ANY non-finite logit. Those
    rows fall back to greedy over the sanitized distribution (``sample``) —
    a partially-poisoned distribution is not one the request asked to
    sample from, and argmax of the surviving finite entries is the most
    conservative defined answer (token 0 when the whole row is bad)."""
    return ~jnp.all(jnp.isfinite(logits), axis=-1)


def apply_top_k(logits: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """Keep each row's k highest logits (k: [B] int32; k <= 0 disables).
    Ties at the threshold all survive — the kept set can exceed k on exact
    ties, which only ever widens the candidate pool."""
    V = logits.shape[-1]
    sorted_desc = -jnp.sort(-logits, axis=-1)
    idx = jnp.clip(k - 1, 0, V - 1)
    thresh = jnp.take_along_axis(sorted_desc, idx[:, None], axis=-1)
    keep = (k <= 0)[:, None] | (logits >= thresh)
    return jnp.where(keep, logits, NEG_INF)


def apply_top_p(logits: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """Nucleus filter (p: [B] float; p >= 1 disables): keep the smallest
    prefix of the descending-probability ordering whose cumulative mass
    reaches p. The top-1 token always survives (its exclusive prefix mass
    is 0 < p)."""
    sorted_desc = -jnp.sort(-logits, axis=-1)
    probs = jax.nn.softmax(sorted_desc.astype(jnp.float32), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (cum - probs) < p[:, None]  # exclusive prefix mass < p
    # p <= 0 would otherwise mask every column (0 < 0 is False) and turn
    # sampling into a constant token-0 emitter; pin the top-1 column True
    keep_sorted = keep_sorted.at[:, 0].set(True)
    cutoff = jnp.min(
        jnp.where(keep_sorted, sorted_desc, jnp.inf), axis=-1)
    keep = (p >= 1.0)[:, None] | (logits >= cutoff[:, None])
    return jnp.where(keep, logits, NEG_INF)


def filter_top_k_top_p(scaled: jnp.ndarray, top_k: jnp.ndarray,
                       top_p: jnp.ndarray) -> jnp.ndarray:
    """Both filters off ONE descending sort (each standalone filter pays its
    own). Equivalent to ``apply_top_p(apply_top_k(scaled, top_k), top_p)``:
    the kept set of the sequential application is a value-cutoff set of
    the sort — top-k keeps values at or above the k-th largest (threshold
    TIES INCLUDED, exactly like ``apply_top_k``: a rank < k mask would
    drop ties and, worse, shrink the softmax normalization the nucleus is
    measured against), top-p keeps a prefix of the (k-masked) nucleus —
    so a single cutoff-by-value reproduces it. ``top_p <= 0`` pins the
    top-1 column like ``apply_top_p`` does — tests/test_inference.py pins
    both properties against the sequential application."""
    V = scaled.shape[-1]
    sorted_desc = -jnp.sort(-scaled, axis=-1)
    idx = jnp.clip(top_k - 1, 0, V - 1)
    thresh = jnp.take_along_axis(sorted_desc, idx[:, None], axis=-1)
    keep = (top_k[:, None] <= 0) | (sorted_desc >= thresh)
    probs = jax.nn.softmax(jnp.where(keep, sorted_desc, NEG_INF), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep &= (top_p[:, None] >= 1.0) | ((cum - probs) < top_p[:, None])
    keep = keep.at[:, 0].set(True)  # the top-1 token always survives
    cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1)
    return jnp.where(scaled >= cutoff[:, None], scaled, NEG_INF)


# transitional alias (pre-PR-3 private name)
_filter_top_k_top_p = filter_top_k_top_p


def sample(logits: jnp.ndarray, key, temperature: jnp.ndarray,
           top_k: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Draw one token per row: greedy where ``temperature == 0``, otherwise
    a categorical over temperature-scaled, top-k- then top-p-filtered
    logits. All sampling params are [B] arrays (see module docstring);
    rows draw independently from one key. An all-greedy batch (the common
    serving default) short-circuits past the sort/softmax/draw pipeline —
    decode pays one argmax per step.

    Rows with non-finite logits fall back to GREEDY over the sanitized
    (non-finite -> NEG_INF) distribution instead of propagating NaN into
    the emitted stream; finite rows are bit-identical to the pre-gate
    sampler (``sanitize_logits`` is the identity there)."""
    bad = nonfinite_rows(logits)
    logits = sanitize_logits(logits)
    greedy_tok = greedy(logits)

    def stochastic():
        t = jnp.maximum(temperature, 1e-6)[:, None]
        filtered = filter_top_k_top_p(
            logits.astype(jnp.float32) / t, top_k, top_p)
        drawn = jax.random.categorical(key, filtered, axis=-1).astype(
            jnp.int32)
        return jnp.where((temperature <= 0.0) | bad, greedy_tok, drawn)

    # no collectives in either branch, so the cond is shard_map-safe
    return jax.lax.cond(jnp.all(temperature <= 0.0),
                        lambda: greedy_tok, stochastic)


# The host-side (eager-call) entry for ``sample``. Called eagerly, the
# ``lax.cond`` above traces and XLA-compiles a FRESH program on every
# invocation — its branch closures are new objects each call, so nothing
# caches and every admit-time first-token draw pays ~quarter-second of
# compile (measured on the CPU backend; bench_decode --overlap surfaced
# it as a fixed per-request cost swamping the pipeline A/B). Under jit
# the cond traces once per argument shape and the executable is cached,
# so admissions after the first are microseconds. Same computation,
# same key discipline — jit only changes where the compile cache lives.
sample_jit = jax.jit(sample)


def sample_rowkeys(logits: jnp.ndarray, keys: jnp.ndarray,
                   temperature: jnp.ndarray, top_k: jnp.ndarray,
                   top_p: jnp.ndarray) -> jnp.ndarray:
    """``sample`` with a PER-ROW key: row b draws with ``keys[b]`` ([B, 2]
    raw uint32 PRNG keys) instead of every row sharing one key. This is
    the per-slot key schedule's sampler (``inference.key_schedule:
    "slot"``, docs/INFERENCE.md "Overlapped scheduling"): the batcher
    derives ``keys[b] = fold_in(base_b, position)`` so a slot's stream
    depends only on its own base key and token position — independent of
    which other slots share the round, of round boundaries, and of
    speculative grouping. Greedy rows, the all-greedy short-circuit, and
    the non-finite fallback behave exactly like ``sample``; a single row
    drawn here is bit-identical to ``sample`` on that row alone with the
    same key (the categorical's noise depends only on the key and the
    row's element count)."""
    bad = nonfinite_rows(logits)
    logits = sanitize_logits(logits)
    greedy_tok = greedy(logits)

    def stochastic():
        t = jnp.maximum(temperature, 1e-6)[:, None]
        filtered = filter_top_k_top_p(
            logits.astype(jnp.float32) / t, top_k, top_p)
        drawn = jax.vmap(
            lambda k, row: jax.random.categorical(k, row))(
                keys, filtered).astype(jnp.int32)
        return jnp.where((temperature <= 0.0) | bad, greedy_tok, drawn)

    # no collectives in either branch, so the cond is shard_map-safe
    return jax.lax.cond(jnp.all(temperature <= 0.0),
                        lambda: greedy_tok, stochastic)


def filtered_probs(logits: jnp.ndarray, temperature: jnp.ndarray,
                   top_k: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """The distribution ``sample`` draws its stochastic rows from:
    softmax over temperature-scaled, top-k/top-p-filtered logits.
    logits [N, V] fp32 with [N] per-row params -> probs [N, V] fp32.
    Non-finite entries are sanitized away first (see ``sanitize_logits``),
    so a poisoned verify dispatch yields a defined distribution."""
    t = jnp.maximum(temperature, 1e-6)[:, None]
    return jax.nn.softmax(
        filter_top_k_top_p(
            sanitize_logits(logits).astype(jnp.float32) / t, top_k, top_p),
        axis=-1)


def _leading_true(ok: jnp.ndarray) -> jnp.ndarray:
    """Length of each row's leading all-True prefix: [B, G] bool -> [B]."""
    return jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)


def speculative_accept(logits: jnp.ndarray, draft: jnp.ndarray, key,
                       temperature: jnp.ndarray, top_k: jnp.ndarray,
                       top_p: jnp.ndarray,
                       draft_len: Optional[jnp.ndarray] = None) -> tuple:
    """Distribution-preserving draft acceptance (Leviathan et al. 2023 /
    Chen et al. 2023 speculative sampling, specialized to a DETERMINISTIC
    drafter: the proposal q is a point mass at the drafted token, so the
    accept probability min(1, p/q) reduces to p(draft) and a rejection
    resamples from the residual norm(max(p - q, 0)) = p with the rejected
    token zeroed, renormalized).

    ``logits`` [B, S, V] fp32 — the verify pass's scores, where
    ``logits[:, i]`` is the target distribution for the token FOLLOWING fed
    token i (S = gamma + 1: the slot's last token plus gamma drafts);
    ``draft`` [B, gamma] int32; ``temperature``/``top_k``/``top_p`` [B]
    per-slot sampling params (the same arrays ``sample`` takes, so the
    target p is exactly the non-speculative sampler's distribution).

    Returns ``(emitted [B, S] int32, counts [B] int32)``: row b's leading
    ``counts[b]`` entries (1 <= counts <= gamma + 1) are the tokens the
    slot emits this dispatch — the accepted draft prefix plus one fresh
    token (the residual resample on rejection, a draw from the bonus
    position when every draft accepted). Positions past ``counts`` are
    pad 0. Greedy rows (temperature <= 0) take the exact-match fast path:
    accept while draft == argmax and emit the argmax correction/bonus — the
    emitted chain IS the greedy chain, so greedy speculative output is
    bit-identical to non-speculative greedy decode. An all-greedy batch
    (the serving default) short-circuits past the filter/softmax/draw
    pipeline entirely.

    ``draft_len`` [B] int32 (optional) makes the verify RAGGED: row b
    proposed only ``draft_len[b] <= gamma`` real drafts, the rest of its
    draft row is pad. Columns at or past a row's draft_len are forced
    mismatches — never accepted, never treated as a rejection event — so
    the fresh token draws from position ``min(acc, draft_len)``'s own
    distribution: a row with draft_len 0 reduces exactly to one
    non-speculative decode step (counts == 1), and every row's emitted
    run is the one its own draft length would have produced solo. None =
    every row drafted the full gamma (the pre-ragged contract).
    """
    B, S, V = logits.shape
    G = S - 1
    cols_g = jnp.arange(G, dtype=jnp.int32)[None, :]
    real = (cols_g < draft_len[:, None]) if draft_len is not None else None
    # sanitized argmax: a poisoned verify row degrades to a defined greedy
    # chain instead of NaN-ordering garbage (identity on finite logits)
    preds = greedy(sanitize_logits(
        logits.reshape(B * S, V))).reshape(B, S)  # [B, S] argmax
    ok_greedy = draft == preds[:, :G]
    if real is not None:
        ok_greedy &= real
    acc_greedy = _leading_true(ok_greedy)
    last_greedy = jnp.take_along_axis(
        preds, acc_greedy[:, None], axis=1)[:, 0]

    def greedy_case():
        return acc_greedy, last_greedy

    def stochastic_case():
        probs = filtered_probs(
            logits.reshape(B * S, V), jnp.repeat(temperature, S),
            jnp.repeat(top_k, S), jnp.repeat(top_p, S)).reshape(B, S, V)
        key_u, key_r = jax.random.split(key)
        # accept draft i with probability p_i(draft_i); acceptance is a
        # leading prefix — the first rejection discards the rest
        p_draft = jnp.take_along_axis(
            probs[:, :G], draft[:, :, None], axis=-1)[..., 0]  # [B, G]
        u = jax.random.uniform(key_u, (B, G))
        ok = u < p_draft
        if real is not None:
            # ragged rows: pad columns can neither accept nor count as a
            # rejection — acceptance simply ends at the row's draft_len
            ok &= real
        acc = _leading_true(ok)
        # the fresh token's distribution: the residual at the rejection
        # position (p with the rejected draft token removed, renormalized),
        # or the untouched bonus-position p when every draft accepted
        p_next = jnp.take_along_axis(probs, acc[:, None, None],
                                     axis=1)[:, 0]  # [B, V]
        rej = jnp.take_along_axis(
            draft, jnp.minimum(acc, G - 1)[:, None], axis=1)[:, 0]
        # a rejection EVENT happened iff acceptance stopped before the
        # row's own draft run ended (ragged rows: before draft_len, not G)
        rejected = (acc < draft_len) if draft_len is not None else (acc < G)
        strip = ((jnp.arange(V)[None, :] == rej[:, None])
                 & rejected[:, None])
        res = jnp.where(strip, 0.0, p_next)
        res = res / jnp.maximum(jnp.sum(res, axis=-1, keepdims=True), 1e-20)
        fresh = jax.random.categorical(
            key_r, jnp.log(jnp.maximum(res, 1e-20)), axis=-1).astype(
            jnp.int32)
        # per-row greedy override inside a mixed batch
        a = jnp.where(temperature <= 0.0, acc_greedy, acc)
        return a, jnp.where(temperature <= 0.0, last_greedy, fresh)

    # no collectives in either branch, so the cond is shard_map-safe
    acc, last = jax.lax.cond(jnp.all(temperature <= 0.0),
                             greedy_case, stochastic_case)
    cols = jnp.arange(S, dtype=jnp.int32)[None, :]
    emitted = jnp.where(cols < acc[:, None],
                        jnp.pad(draft, ((0, 0), (0, 1))), 0)
    emitted = jnp.where(cols == acc[:, None], last[:, None], emitted)
    return emitted, acc + 1


def speculative_match(logits: jnp.ndarray, draft: jnp.ndarray,
                      base_keys: jnp.ndarray, positions: jnp.ndarray,
                      temperature: jnp.ndarray, top_k: jnp.ndarray,
                      top_p: jnp.ndarray,
                      draft_len: Optional[jnp.ndarray] = None) -> tuple:
    """Draft acceptance for the per-slot key schedule: sample-and-match.

    Under ``key_schedule: "slot"`` every token position has ONE
    predetermined key (``fold_in(base, position)``), so the verify pass
    can simply draw the target chain's own token at every fed position —
    ``s[b, i] = sample_rowkeys(logits[b, i], fold_in(base_b,
    positions[b, i]))`` — and accept the draft prefix that MATCHES it:
    where draft == s the draft saved a dispatch, where it first diverges
    the emitted token is s itself (the correction), and the bonus
    position's s rides free when everything matched. The emitted stream
    is therefore a pure function of (base key, positions, logits): it
    never depends on the draft VALUES, which is what makes speculative
    output — greedy and stochastic alike — bit-identical to token-by-token
    decode under the same schedule, through any drafter/controller
    trajectory and any round structure (including the overlap pipeline's
    one-round-stale drafts). For a deterministic (point-mass) drafter
    this is exactly rejection sampling: accept-with-p(draft) reduces to
    "accepted iff the chain's own draw equals the draft".

    Arguments mirror ``speculative_accept``; ``base_keys`` [B, 2] raw
    uint32 per-slot keys, ``positions`` [B, S] int32 — the KV row index
    each fed token was written at (``pos0 + i``), i.e. the fold_in data
    the non-speculative chain would use for the same draw. Returns
    ``(emitted [B, S], counts [B])`` with identical conventions."""
    B, S, V = logits.shape
    G = S - 1
    keys = jax.vmap(jax.vmap(jax.random.fold_in, in_axes=(None, 0)))(
        base_keys, positions)  # [B, S, 2]
    s = sample_rowkeys(
        logits.reshape(B * S, V), keys.reshape(B * S, 2),
        jnp.repeat(temperature, S), jnp.repeat(top_k, S),
        jnp.repeat(top_p, S)).reshape(B, S)
    ok = draft == s[:, :G]
    if draft_len is not None:
        # ragged rows: pad columns are forced mismatches, so acceptance
        # ends at the row's own draft_len and the correction draws from
        # that position — same contract as speculative_accept
        cols_g = jnp.arange(G, dtype=jnp.int32)[None, :]
        ok &= cols_g < draft_len[:, None]
    acc = _leading_true(ok)
    cols = jnp.arange(S, dtype=jnp.int32)[None, :]
    # for i < acc, s == draft by construction: emitting s everywhere up
    # to and including the correction/bonus column IS the target chain
    emitted = jnp.where(cols <= acc[:, None], s, 0)
    return emitted, acc + 1
