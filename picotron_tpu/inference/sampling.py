"""Sampling over next-token logits: greedy, temperature, top-k, top-p.

Pure jittable functions over full-vocab logits ``[B, V]`` with PER-REQUEST
parameter arrays ``[B]`` — one compiled program serves a continuous batch
whose slots carry different settings (a slot's params change between steps
without recompiling, because they are array values, not trace constants).

Filter order follows the de-facto HF convention: temperature scaling first,
then top-k, then top-p on the rescaled distribution. ``temperature == 0``
means greedy (argmax) for that row; ``top_k <= 0`` and ``top_p >= 1``
disable their filters. Masked logits use the same large-negative fill as
ops/attention.py so fully-filtered rows stay finite.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from picotron_tpu.ops.attention import NEG_INF


def greedy(logits: jnp.ndarray) -> jnp.ndarray:
    """Argmax decode: [B, V] -> [B] int32."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def apply_top_k(logits: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """Keep each row's k highest logits (k: [B] int32; k <= 0 disables).
    Ties at the threshold all survive — the kept set can exceed k on exact
    ties, which only ever widens the candidate pool."""
    V = logits.shape[-1]
    sorted_desc = -jnp.sort(-logits, axis=-1)
    idx = jnp.clip(k - 1, 0, V - 1)
    thresh = jnp.take_along_axis(sorted_desc, idx[:, None], axis=-1)
    keep = (k <= 0)[:, None] | (logits >= thresh)
    return jnp.where(keep, logits, NEG_INF)


def apply_top_p(logits: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """Nucleus filter (p: [B] float; p >= 1 disables): keep the smallest
    prefix of the descending-probability ordering whose cumulative mass
    reaches p. The top-1 token always survives (its exclusive prefix mass
    is 0 < p)."""
    sorted_desc = -jnp.sort(-logits, axis=-1)
    probs = jax.nn.softmax(sorted_desc.astype(jnp.float32), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (cum - probs) < p[:, None]  # exclusive prefix mass < p
    # p <= 0 would otherwise mask every column (0 < 0 is False) and turn
    # sampling into a constant token-0 emitter; pin the top-1 column True
    keep_sorted = keep_sorted.at[:, 0].set(True)
    cutoff = jnp.min(
        jnp.where(keep_sorted, sorted_desc, jnp.inf), axis=-1)
    keep = (p >= 1.0)[:, None] | (logits >= cutoff[:, None])
    return jnp.where(keep, logits, NEG_INF)


def filter_top_k_top_p(scaled: jnp.ndarray, top_k: jnp.ndarray,
                       top_p: jnp.ndarray) -> jnp.ndarray:
    """Both filters off ONE descending sort (each standalone filter pays its
    own). Equivalent to ``apply_top_p(apply_top_k(scaled, top_k), top_p)``:
    the kept set of the sequential application is a value-cutoff set of
    the sort — top-k keeps values at or above the k-th largest (threshold
    TIES INCLUDED, exactly like ``apply_top_k``: a rank < k mask would
    drop ties and, worse, shrink the softmax normalization the nucleus is
    measured against), top-p keeps a prefix of the (k-masked) nucleus —
    so a single cutoff-by-value reproduces it. ``top_p <= 0`` pins the
    top-1 column like ``apply_top_p`` does — tests/test_inference.py pins
    both properties against the sequential application."""
    V = scaled.shape[-1]
    sorted_desc = -jnp.sort(-scaled, axis=-1)
    idx = jnp.clip(top_k - 1, 0, V - 1)
    thresh = jnp.take_along_axis(sorted_desc, idx[:, None], axis=-1)
    keep = (top_k[:, None] <= 0) | (sorted_desc >= thresh)
    probs = jax.nn.softmax(jnp.where(keep, sorted_desc, NEG_INF), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep &= (top_p[:, None] >= 1.0) | ((cum - probs) < top_p[:, None])
    keep = keep.at[:, 0].set(True)  # the top-1 token always survives
    cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1)
    return jnp.where(scaled >= cutoff[:, None], scaled, NEG_INF)


# transitional alias (pre-PR-3 private name)
_filter_top_k_top_p = filter_top_k_top_p


def sample(logits: jnp.ndarray, key, temperature: jnp.ndarray,
           top_k: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Draw one token per row: greedy where ``temperature == 0``, otherwise
    a categorical over temperature-scaled, top-k- then top-p-filtered
    logits. All sampling params are [B] arrays (see module docstring);
    rows draw independently from one key. An all-greedy batch (the common
    serving default) short-circuits past the sort/softmax/draw pipeline —
    decode pays one argmax per step."""
    greedy_tok = greedy(logits)

    def stochastic():
        t = jnp.maximum(temperature, 1e-6)[:, None]
        filtered = filter_top_k_top_p(
            logits.astype(jnp.float32) / t, top_k, top_p)
        drawn = jax.random.categorical(key, filtered, axis=-1).astype(
            jnp.int32)
        return jnp.where(temperature <= 0.0, greedy_tok, drawn)

    # no collectives in either branch, so the cond is shard_map-safe
    return jax.lax.cond(jnp.all(temperature <= 0.0),
                        lambda: greedy_tok, stochastic)
