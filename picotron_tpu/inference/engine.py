"""The batched generation engine: jitted prefill / decode_step on the mesh.

Serving counterpart of ``train_step.py``. Two compiled programs cover a
request's whole life:

- ``prefill(params, prompt)``: the full-sequence model (the SAME
  ``decoder_layer`` path training runs, flash-capable on TPU) over a
  right-padded prompt bucket, returning the per-layer compact K/V blocks
  plus the last real token's full-vocab logits. Prompts are padded to
  power-of-two buckets so arbitrary lengths reuse a handful of compiled
  shapes; pad rows are inert (causal mask ahead, length mask behind).
- ``decode_step(params, cache, tokens, key, temperature, top_k, top_p)``:
  one token for EVERY slot at once — embed, scan the stacked layers with
  per-slot cache writes and masked dot-product attention
  (kv_cache.decode_attention), head, and per-slot sampling — returning the
  updated cache and the sampled tokens. Slots sit at independent positions
  (``cache['lengths']``); RoPE is applied at each slot's own offset
  (ops/rope.rope_at_positions).

Sharding: the engine builds (or is handed) a ``('dp','pp','cp','tp')`` mesh
with dp=pp=cp=1 and runs both programs under shard_map with the model's
training PartitionSpecs — a TP-sharded checkpoint loads and decodes without
resharding; the cache's head axis shards over 'tp' alongside the wk/wv
columns that fill it. Pipeline- or interleave-trained checkpoints are
handled at LOAD time (checkpoint.CheckpointManager.load / load_params remap
stacked layer rows to the contiguous pp=1 layout), so the engine always
sees a plain [L] stack.

The cache is donated through decode_step and insert, so steady-state decode
updates the K/V buffers in place — no per-token reallocation.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from picotron_tpu.config import Config
from picotron_tpu.inference import kv_cache, sampling
from picotron_tpu.models import llama
from picotron_tpu.ops.rope import precompute_rope, rope_at_positions
from picotron_tpu.parallel.tp import tp_gather
from picotron_tpu.topology import Topology, build_topology, named_shardings
from picotron_tpu.utils import shard_map


def inference_config(cfg: Config) -> Config:
    """Derive the serving config from a training config: same model, but a
    tp-only topology (dp=pp=cp=1) with the training-only rewrites (sequence
    parallelism, fsdp/zero1, vma checking) off — none of them make sense at
    query length 1, and sequence parallelism cannot even shard it."""
    raw = cfg.to_dict()
    raw["distributed"].update(dict(
        dp_size=1, pp_size=1, cp_size=1, pp_interleave=1,
        tp_sequence_parallel=False, fsdp=False, zero1=False,
        check_vma=False, cp_zigzag=False))
    return Config.from_dict(raw)


class InferenceEngine:
    """Fixed-slot generation engine over a tp mesh.

    ``slots`` is the decode batch width: the continuous batcher admits and
    retires requests into these fixed positions so the compiled decode
    program never changes shape. ``max_seq_len`` bounds prompt + generated
    tokens per slot (default: the model's max_position_embeddings).
    """

    def __init__(self, cfg: Config, topo: Optional[Topology] = None, *,
                 slots: int = 8, max_seq_len: Optional[int] = None,
                 cache_dtype=None, min_prefill_bucket: int = 16):
        self.cfg = inference_config(cfg)
        m, d = self.cfg.model, self.cfg.distributed
        if topo is None:
            topo = build_topology(1, 1, 1, d.tp_size)
        if (topo.dp_size, topo.pp_size, topo.cp_size) != (1, 1, 1):
            raise ValueError(
                "InferenceEngine serves a tp-only mesh (dp=pp=cp=1); got "
                f"dp={topo.dp_size} pp={topo.pp_size} cp={topo.cp_size}. "
                "Data-parallel serving = one engine per replica.")
        if topo.tp_size != d.tp_size:
            raise ValueError(
                f"mesh tp={topo.tp_size} != config tp_size={d.tp_size}")
        self.topo = topo
        self.slots = int(slots)
        self.max_seq_len = int(max_seq_len or m.max_position_embeddings)
        self.min_prefill_bucket = int(min_prefill_bucket)
        self.cache_dtype = jnp.dtype(cache_dtype or m.dtype)
        self._dt = jnp.dtype(m.dtype)

        # angle tables cover the whole cache window; decode gathers rows at
        # each slot's own offset
        self._cos, self._sin = precompute_rope(
            self.max_seq_len, m.head_dim, m.rope_theta, self._dt)

        self._pspecs = llama.param_pspecs(m)
        self._cspecs = kv_cache.cache_pspecs()
        kv_spec = {"k": self._cspecs["k"], "v": self._cspecs["v"]}
        mesh = topo.mesh

        self._prefill_jit = jax.jit(shard_map(
            self._prefill_impl, mesh,
            in_specs=(self._pspecs, P(), P()),
            out_specs=(kv_spec, P())))
        self._decode_jit = jax.jit(shard_map(
            self._decode_impl, mesh,
            in_specs=(self._pspecs, self._cspecs, P(), P(), P(), P(), P()),
            out_specs=(self._cspecs, P(), P())),
            donate_argnums=(1,))
        self._insert_jit = jax.jit(kv_cache.insert_prefill,
                                   donate_argnums=(0,))
        self._release_jit = jax.jit(kv_cache.release, donate_argnums=(0,))
        self._init_cache_jit = jax.jit(
            partial(kv_cache.init_cache, m, self.slots, self.max_seq_len,
                    dtype=self.cache_dtype),
            out_shardings=named_shardings(topo, self._cspecs))

    # ---- model programs (run inside shard_map; tp axis collectives live) --

    def _prefill_impl(self, params, tokens, length):
        """tokens [1, S_bucket] int32, length [1] -> (kv blocks, last-token
        logits [1, V]). Pad tokens beyond ``length`` produce K/V rows the
        length mask makes unreachable."""
        cfg = self.cfg
        S = tokens.shape[1]
        cos_l = lax.dynamic_slice_in_dim(self._cos, 0, S, 0)
        sin_l = lax.dynamic_slice_in_dim(self._sin, 0, S, 0)
        h = llama.embed_lookup(params["embed"], tokens).astype(self._dt)

        def body(hc, lp):
            hc, kv = llama.decoder_layer(lp, hc, cos_l, sin_l, cfg,
                                         return_kv=True)
            return hc, kv

        h, (K, V) = lax.scan(body, h, params["layers"])
        # only the last real token's logits are consumed: slice its hidden
        # row BEFORE the LM-head matmul and the vocab tp-gather, so the
        # bucket pays one [1, H] @ [H, V] row instead of S_bucket of them
        h_last = jnp.take_along_axis(h, (length - 1)[:, None, None], axis=1)
        last = tp_gather(llama.head_logits(params, h_last, cfg))[:, 0]
        return {"k": K.astype(self.cache_dtype),
                "v": V.astype(self.cache_dtype)}, last.astype(jnp.float32)

    def _decode_impl(self, params, cache, tokens, key, temperature,
                     top_k, top_p):
        """One autoregressive step for all slots: tokens [B] (each slot's
        current last token), cache lengths give every slot its position."""
        cfg = self.cfg
        pos = cache["lengths"]  # [B] write index of the incoming token
        cos_b, sin_b = rope_at_positions(self._cos, self._sin, pos)
        h = llama.embed_lookup(params["embed"],
                               tokens[:, None]).astype(self._dt)

        def body(hc, xs):
            lp, kc, vc = xs
            hc, (kc, vc) = llama.decoder_layer(
                lp, hc, cos_b, sin_b, cfg, cache=(kc, vc), pos=pos)
            return hc, (kc, vc)

        h, (K, V) = lax.scan(body, h, (params["layers"], cache["k"],
                                       cache["v"]))
        logits = tp_gather(llama.head_logits(params, h, cfg))[:, 0]
        logits = logits.astype(jnp.float32)
        next_tok = sampling.sample(logits, key, temperature, top_k, top_p)
        # free slots (length 0) ride along for shape stability but stay at
        # length 0 — their row-0 writes are never visible
        new_cache = {"k": K, "v": V,
                     "lengths": jnp.where(pos > 0, pos + 1, 0)}
        return new_cache, next_tok, logits

    # ---- host-facing API ---------------------------------------------------

    def shard_params(self, params):
        """Place a (global) parameter pytree onto this engine's mesh with
        the model's training shardings — TP column/row splits land on their
        devices, no resharding at step time."""
        return jax.tree.map(jax.device_put, params,
                            named_shardings(self.topo, self._pspecs))

    def init_cache(self) -> dict:
        """Fresh zeroed cache, sharded on the engine mesh."""
        return self._init_cache_jit()

    def prefill_bucket(self, prompt_len: int) -> int:
        """Power-of-two padding bucket for a prompt (one compile each)."""
        if prompt_len > self.max_seq_len:
            raise ValueError(
                f"prompt of {prompt_len} tokens exceeds max_seq_len "
                f"{self.max_seq_len}")
        b = self.min_prefill_bucket
        while b < prompt_len:
            b *= 2
        return min(b, self.max_seq_len)

    def prefill(self, params, prompt_ids) -> tuple:
        """Run one prompt through the full-sequence model. Returns
        (kv_blocks, last_logits [1, V] fp32). Pads to the prompt's bucket
        host-side; jit reuses one executable per bucket size."""
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError("empty prompt")
        bucket = self.prefill_bucket(ids.size)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, : ids.size] = ids
        return self._prefill_jit(params, jnp.asarray(padded),
                                 jnp.asarray([ids.size], jnp.int32))

    def insert(self, cache, kv, slot: int, length: int) -> dict:
        """Park a prefill's blocks into ``slot`` (consumes ``cache``)."""
        return self._insert_jit(cache, kv, slot, length)

    def release(self, cache, slot: int) -> dict:
        """Free a slot for the next request (consumes ``cache``)."""
        return self._release_jit(cache, slot)

    def decode_step(self, params, cache, tokens, key, temperature,
                    top_k, top_p) -> tuple:
        """One token for every slot. tokens/temperature/top_k/top_p are
        [slots] host or device arrays; returns (cache, next_tokens [slots],
        logits [slots, V] fp32). Consumes ``cache``."""
        return self._decode_jit(
            params, cache,
            jnp.asarray(np.asarray(tokens, np.int32)), key,
            jnp.asarray(np.asarray(temperature, np.float32)),
            jnp.asarray(np.asarray(top_k, np.int32)),
            jnp.asarray(np.asarray(top_p, np.float32)))
