"""The batched generation engine: jitted prefill / decode programs on the mesh.

Serving counterpart of ``train_step.py``. Three compiled program families
cover a request's whole life:

- ``prefill(params, prompt)``: the full-sequence model (the SAME
  ``decoder_layer`` path training runs, flash-capable on TPU) over a
  right-padded prompt bucket, returning the per-layer compact K/V blocks
  (quantized for int8 caches) plus the last real token's full-vocab logits.
  Prompts are padded to power-of-two buckets so arbitrary lengths reuse a
  handful of compiled shapes; pad rows are inert (causal mask ahead, length
  mask behind). Prompts longer than ``prefill_chunk`` instead run
  ``prefill_chunked``: fixed-width chunk dispatches that attend causally
  over the already-written cache prefix plus the current chunk and write
  K/V straight into the target slot — O(1) compiled shapes in prompt
  length, flat peak activation memory.
- ``decode_block(params, cache, tokens, keys, eos_id, budget, ...)``:
  ``decode_block_len`` autoregressive steps for EVERY slot inside ONE
  jitted program (``lax.scan`` over steps). Per-slot stop state lives on
  device — ``eos_id`` [B] (−1 = none), remaining-token ``budget`` [B], and
  the active mask derived from ``cache['lengths']`` — so a slot that hits
  EOS or exhausts its budget mid-block goes inactive, emits pad tokens,
  and stops advancing its cache length: the block result is exactly what
  that many single steps would have produced. One host sync per block
  instead of per token. ``decode_block_len == 1`` is the classic per-token
  loop.
- ``decode_step(...)``: the single-token program (kept for callers that
  want per-token logits; the batcher drives ``decode_block``).
- ``verify(params, cache, tokens, key, ...)`` (``spec_len > 0``): the
  speculative-decoding verify pass — ONE dispatch scores ``spec_len + 1``
  positions per slot (each slot's last token plus ``spec_len`` drafted
  continuation tokens), writing the drafted K/V into the slot
  OPTIMISTICALLY (int8 caches quantize on write as always), then applies
  the distribution-preserving acceptance rule on device
  (sampling.speculative_accept) and rewinds each slot's length pointer to
  its accepted prefix — the rejected rows become stale K/V beyond the
  length mask, exactly like a freed slot's. Each dispatch emits 1 to
  ``spec_len + 1`` tokens per slot.

Sharding: the engine builds (or is handed) a ``('dp','pp','cp','tp')`` mesh
with dp=pp=cp=1 and runs the programs under shard_map with the model's
training PartitionSpecs — a TP-sharded checkpoint loads and decodes without
resharding; the cache's head axis (and the int8 scale tensors' head axis)
shards over 'tp' alongside the wk/wv columns that fill it. Pipeline- or
interleave-trained checkpoints are handled at LOAD time
(checkpoint.CheckpointManager.load / load_params remap stacked layer rows
to the contiguous pp=1 layout), so the engine always sees a plain [L] stack.

The cache is donated through every decode/insert/chunk program, so
steady-state generation updates the K/V buffers in place — no per-token
reallocation.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from picotron_tpu import comm_trace
from picotron_tpu.config import Config
from picotron_tpu.inference import kv_cache, paged_kv, sampling
from picotron_tpu.obs import Obs
from picotron_tpu.models import llama
from picotron_tpu.ops.rope import precompute_rope, rope_at_positions
from picotron_tpu.parallel.tp import tp_gather
from picotron_tpu.topology import Topology, build_topology, named_shardings
from picotron_tpu.utils import log0, shard_map

# Process-wide graceful-degradation latch (inference.attend_fallback): once
# a flash dispatch has failed, every engine in this process — current and
# future — serves on "dense". A kernel that broke once is not re-trusted
# mid-serve; restarting the process is the way to re-arm flash.
_FLASH_BROKEN = False


def inference_config(cfg: Config) -> Config:
    """Derive the serving config from a training config: same model, but a
    ('dp','tp') topology (pp=cp=1) with the training-only rewrites (sequence
    parallelism, fsdp/zero1, vma checking) off — none of them make sense at
    query length 1, and sequence parallelism cannot even shard it. The
    serving dp width comes from ``inference.dp_size`` (NOT the training
    ``distributed.dp_size``, which shards gradients, not slots); 1 — the
    default — is the historical tp-only mesh."""
    raw = cfg.to_dict()
    dp = int((raw.get("inference") or {}).get("dp_size", 1) or 1)
    raw["distributed"].update(dict(
        dp_size=dp, pp_size=1, cp_size=1, pp_interleave=1,
        tp_sequence_parallel=False, fsdp=False, zero1=False,
        check_vma=False, cp_zigzag=False))
    return Config.from_dict(raw)


class InferenceEngine:
    """Fixed-slot generation engine over a tp mesh.

    ``slots`` is the decode batch width: the continuous batcher admits and
    retires requests into these fixed positions so the compiled decode
    program never changes shape. ``max_seq_len`` bounds prompt + generated
    tokens per slot (default: the model's max_position_embeddings).
    ``decode_block_len`` / ``kv_cache_dtype`` / ``prefill_chunk`` /
    ``attend_impl`` default from ``cfg.inference`` (config.InferenceConfig);
    keyword overrides win. ``attend_impl="flash"`` routes every cache
    attend (decode, verify, chunked prefill) through the length-aware
    Pallas flash-decode kernel instead of the dense whole-window einsum.
    """

    def __init__(self, cfg: Config, topo: Optional[Topology] = None, *,
                 slots: int = 8, max_seq_len: Optional[int] = None,
                 cache_dtype=None, min_prefill_bucket: int = 16,
                 decode_block_len: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 spec_len: Optional[int] = None,
                 spec_ngram: Optional[int] = None,
                 attend_impl: Optional[str] = None,
                 kv_layout: Optional[str] = None,
                 kv_page_len: Optional[int] = None,
                 kv_num_pages: Optional[int] = None,
                 kv_page_policy: Optional[str] = None,
                 sample_on_device: Optional[bool] = None,
                 weight_dtype: Optional[str] = None,
                 drafter: Optional[str] = None,
                 return_hidden: Optional[bool] = None,
                 overlap: Optional[bool] = None,
                 mixed_dispatch: Optional[bool] = None,
                 key_schedule: Optional[str] = None,
                 hooks=None, adapters=None):
        self.cfg = inference_config(cfg)
        m, d = self.cfg.model, self.cfg.distributed
        inf = self.cfg.inference
        self.dp_size = int(inf.dp_size or 1)
        if self.dp_size < 1:
            raise ValueError("inference.dp_size must be >= 1")
        if topo is None:
            topo = build_topology(self.dp_size, 1, 1, d.tp_size)
        if (topo.pp_size, topo.cp_size) != (1, 1) \
                or topo.dp_size != self.dp_size:
            raise ValueError(
                "InferenceEngine serves a ('dp','tp') mesh (pp=cp=1) whose "
                f"dp width matches inference.dp_size={self.dp_size}; got "
                f"dp={topo.dp_size} pp={topo.pp_size} cp={topo.cp_size}. "
                "Set inference.dp_size to shard ONE logical engine's slot "
                "axis over dp shards (1 = the tp-only default; scale-out "
                "beyond that is still one engine per replica behind the "
                "router).")
        if topo.tp_size != d.tp_size:
            raise ValueError(
                f"mesh tp={topo.tp_size} != config tp_size={d.tp_size}")
        self.topo = topo
        self.slots = int(slots)
        if self.slots % self.dp_size:
            raise ValueError(
                f"slots ({self.slots}) must divide evenly over "
                f"inference.dp_size ({self.dp_size}) — each dp shard "
                "serves slots/dp of the batch")
        self.slots_per_shard = self.slots // self.dp_size
        # optional ClusterMonitor lease guard (attach_monitor): multi-host
        # dp serving checks peer liveness before every dispatch collective
        self.monitor = None
        self.max_seq_len = int(max_seq_len or m.max_position_embeddings)
        self.min_prefill_bucket = int(min_prefill_bucket)
        self.decode_block_len = int(decode_block_len
                                    if decode_block_len is not None
                                    else inf.decode_block_len)
        if self.decode_block_len < 1:
            raise ValueError("decode_block_len must be >= 1")
        self.prefill_chunk = int(prefill_chunk if prefill_chunk is not None
                                 else inf.prefill_chunk)
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.spec_len = int(spec_len if spec_len is not None
                            else inf.spec_len)
        if self.spec_len < 0:
            raise ValueError("spec_len must be >= 0 (0 = off)")
        self.spec_ngram = int(spec_ngram if spec_ngram is not None
                              else inf.spec_ngram)
        if self.spec_ngram < 1:
            raise ValueError("spec_ngram must be >= 1")
        # Drafter selection (inference.drafter): "ngram" keeps drafting
        # host-side; "learned" is the EAGLE-style head over the target's
        # last hidden state, which needs that state plumbed out of every
        # dispatch — the return_hidden hook below (PR 1's return_kv
        # pattern: a trace-time output the programs grow only when asked).
        if drafter is not None:
            if drafter not in ("ngram", "learned"):
                raise ValueError(
                    f"unknown drafter {drafter!r} (ngram|learned)")
            inf.drafter = drafter
        self.drafter_kind = inf.drafter
        if return_hidden is None:
            return_hidden = (self.spec_len > 0
                             and self.drafter_kind == "learned")
        self.return_hidden = bool(return_hidden)
        # KV-cache attention kernel for decode/verify/chunked prefill:
        # "dense" (whole-window reference) or "flash" (length-aware Pallas
        # flash decode). A Python-level choice, so every jitted program
        # below traces the selected kernel statically — no runtime branch,
        # one executable per impl. The override lands in self.cfg BEFORE
        # the jit wrappers close over it.
        if attend_impl is not None:
            if attend_impl not in ("dense", "flash"):
                raise ValueError(
                    f"unknown attend_impl {attend_impl!r} (dense|flash)")
            inf.attend_impl = attend_impl
        if (inf.attend_impl == "flash" and inf.attend_fallback
                and _FLASH_BROKEN):
            # the process-wide degradation latch: flash already failed here
            log0("attend_impl 'flash' already failed in this process; "
                 "this engine starts on 'dense' (inference.attend_fallback)")
            inf.attend_impl = "dense"
        self.attend_impl = inf.attend_impl
        # Fused on-device sampling epilogue: prefill/chunked-prefill/
        # decode_step dispatches sample INSIDE the jitted program and
        # return token ids instead of [*, vocab] logits (decode_block and
        # verify always did). A trace-time choice like attend_impl: the
        # programs below are built with or without the epilogue.
        if sample_on_device is not None:
            inf.sample_on_device = bool(sample_on_device)
        self.sample_on_device = inf.sample_on_device
        # Zero-bubble overlapped scheduling + PRNG key schedule
        # (docs/INFERENCE.md "Overlapped scheduling"). overlap is the
        # BATCHER's pipeline switch; the engine carries it so the batcher,
        # serve front end, and bench all read one resolved source of
        # truth. key_schedule decides how sampled tokens are keyed:
        # "round" (one fresh key per dispatch — the historical schedule)
        # or "slot" (token at position p keyed fold_in(base_slot, p-1) —
        # round-structure-independent, which is what lets the pipeline
        # reorder rounds without moving a single sampled token). "auto"
        # resolves to "slot" iff overlap is on, so the default-off path
        # keeps today's programs byte-identical.
        if overlap is not None:
            inf.overlap = bool(overlap)
        if mixed_dispatch is not None:
            inf.mixed_dispatch = bool(mixed_dispatch)
        if key_schedule is not None:
            inf.key_schedule = key_schedule
        self.overlap = bool(inf.overlap)
        # Mixed prefill–decode dispatch (docs/INFERENCE.md "Mixed
        # prefill–decode dispatch"): every decode/verify dispatch also
        # advances one fixed-width prefill LANE (prefill_chunk tokens,
        # padded/masked when idle so the compiled shape never changes).
        # Like overlap, mixed streams must be keyed per slot so the lane's
        # round placement cannot move a sampled token.
        self.mixed = bool(inf.mixed_dispatch)
        ks = inf.key_schedule
        if ks not in ("auto", "round", "slot"):
            raise ValueError(
                f"unknown key_schedule {ks!r} (auto|round|slot)")
        if ks == "auto":
            ks = "slot" if (self.overlap or self.mixed) else "round"
        elif ks == "round" and self.overlap:
            raise ValueError(
                "overlap requires the per-slot key schedule — round-keyed "
                "sampling ties streams to round boundaries; use "
                "key_schedule='slot' (or 'auto')")
        elif ks == "round" and self.mixed:
            raise ValueError(
                "mixed_dispatch requires the per-slot key schedule — "
                "round-keyed sampling ties streams to round boundaries, "
                "which fusing the prefill lane changes; use "
                "key_schedule='slot' (or 'auto')")
        self.key_schedule = ks
        # Deferred paged length advance: the overlapped batcher's sync
        # stage owns host_len bookkeeping (apply_advance) because at issue
        # time the previous round's counts are still on device. Off by
        # default; ContinuousBatcher flips it when it runs the pipeline.
        self.defer_advance = False
        # Weight storage format (inference.weight_dtype): "bf16" keeps the
        # dense params tree; "int8" expects the per-channel quantized tree
        # (checkpoint.load_* with weight_dtype="int8", or
        # llama.quantize_params) — every matmul site dispatches on the
        # LEAF form at trace time (models/llama.py::matmul), so the only
        # engine-side difference is the pspec tree shard_params places
        # against (scales shard over 'tp' with their channels).
        if weight_dtype is not None:
            if weight_dtype not in ("bf16", "int8"):
                raise ValueError(
                    f"unknown weight_dtype {weight_dtype!r} (bf16|int8)")
            inf.weight_dtype = weight_dtype
        self.weight_dtype = inf.weight_dtype
        self.quant_weights = self.weight_dtype == "int8"
        # Telemetry (picotron_tpu/obs, docs/OBSERVABILITY.md): every
        # engine owns a fresh metrics registry (counters start at zero
        # per server) and shares the process span ring. The batcher and
        # serve front end reuse this bundle, so one /metrics page covers
        # the whole serving stack. obs.enabled: false swaps in no-ops.
        self.obs = Obs.from_config(self.cfg.obs)
        # dispatch hooks (fault injection / observation): an object with
        # before_dispatch(kind, active_slots) — may raise or sleep — and
        # poison_logits(kind) -> bool (route this dispatch through the
        # NaN-poisoned decode program). resilience.chaos.ServingChaos is
        # the shipped implementation; None = no hooks.
        self.hooks = hooks
        # a chunk wider than the cache window could never be written
        # (mirrors prefill_bucket's min(bucket, max_seq_len) cap)
        self.prefill_chunk = min(self.prefill_chunk, self.max_seq_len)
        # int8 is accepted through either the config knob or cache_dtype
        # (string "int8", jnp.int8, or np.dtype — normalized, so the dtype
        # spelling can't silently build an unquantized int8 cache); an
        # EXPLICIT cache_dtype wins over the config, so a caller can turn
        # quantization off as well as on
        if cache_dtype is not None:
            self.quantized = jnp.dtype(cache_dtype) == jnp.dtype(jnp.int8)
        else:
            self.quantized = inf.kv_cache_dtype == "int8"
        self.cache_dtype = (jnp.dtype(jnp.int8) if self.quantized
                            else jnp.dtype(cache_dtype or m.dtype))
        self._dt = jnp.dtype(m.dtype)

        # KV memory layout: "contiguous" (per-slot strips — the pinned
        # default) or "paged" (block-table indirection over a global page
        # pool with refcounted prefix sharing + copy-on-write —
        # inference/paged_kv.py). A Python-level choice like attend_impl:
        # every jitted program traces the selected layout statically.
        if kv_layout is not None:
            if kv_layout not in ("contiguous", "paged"):
                raise ValueError(
                    f"unknown kv_layout {kv_layout!r} (contiguous|paged)")
            inf.kv_layout = kv_layout
        self.kv_layout = inf.kv_layout
        # Per-page storage policy (hot_bf16: shared pages read full
        # precision, exclusive tails read int8) — paged-only, mutually
        # exclusive with a uniformly int8 cache (config.validate mirrors
        # both checks for the JSON path; the kwargs path lands here).
        if kv_page_policy is not None:
            if kv_page_policy not in ("uniform", "hot_bf16"):
                raise ValueError(
                    f"unknown kv_page_policy {kv_page_policy!r} "
                    "(uniform|hot_bf16)")
            inf.kv_page_policy = kv_page_policy
        self.kv_page_policy = inf.kv_page_policy
        if self.kv_page_policy == "hot_bf16":
            if self.kv_layout != "paged":
                raise ValueError(
                    "kv_page_policy 'hot_bf16' requires kv_layout='paged' "
                    "(per-page refcounts decide which pages read as int8); "
                    "set kv_layout='paged' or keep kv_page_policy="
                    "'uniform'")
            if self.quantized:
                raise ValueError(
                    "kv_page_policy 'hot_bf16' is mutually exclusive with "
                    "an int8 cache (it manages its own quantized "
                    "representation); drop cache_dtype/kv_cache_dtype "
                    "'int8' or keep kv_page_policy='uniform'")
        self.page_policy = self.kv_page_policy == "hot_bf16"
        self.paged: Optional[paged_kv.PagedKV] = None
        if self.kv_layout == "paged":
            self.page_len = int(kv_page_len or inf.kv_page_len)
            if self.page_len < 8 or self.page_len & (self.page_len - 1):
                raise ValueError(
                    f"kv_page_len must be a power of two >= 8, got "
                    f"{self.page_len}")
            # logical window per slot, in pages (>= max_seq_len rows)
            self.max_pages = -(-self.max_seq_len // self.page_len)
            self.num_pages = int(
                kv_num_pages or inf.kv_num_pages
                or self.dp_size * (1 + self.slots_per_shard
                                   * self.max_pages))
            if self.num_pages < 2:
                raise ValueError("kv_num_pages must be >= 2 "
                                 "(page 0 is the reserved NULL page)")
            if self.dp_size > 1:
                # dp-sharded pool: each shard runs its own PagedKV over a
                # pages_per_shard strip (local page 0 = that shard's NULL
                # page); the engine sees global slot/page ids through the
                # ShardedPagedKV facade.
                self.paged = paged_kv.ShardedPagedKV(
                    self.dp_size, self.slots, self.page_len, self.max_pages,
                    self.num_pages, prefix_cache=inf.prefix_cache)
                self.pages_per_shard = self.paged.pages_per_shard
            else:
                self.paged = paged_kv.PagedKV(
                    self.slots, self.page_len, self.max_pages,
                    self.num_pages, prefix_cache=inf.prefix_cache)
                self.pages_per_shard = self.num_pages

        # angle tables cover the whole cache window; decode gathers rows at
        # each slot's own offset
        self._cos, self._sin = precompute_rope(
            self.max_seq_len, m.head_dim, m.rope_theta, self._dt)

        self._pspecs = llama.param_pspecs(m, weight_dtype=self.weight_dtype)
        # Multi-tenant adapter pack (inference/tenancy.py): when present,
        # every dispatch binds per-row adapter ids into the params tree
        # (llama.bind_adapters) and the compiled programs grow the
        # adapter operands — a trace-time leaf-form change on the same
        # seam weight quantization rides, so adapter-less engines build
        # byte-identical programs to the pre-tenancy engine.
        # ``shard_params`` keeps placing the BASE tree (self._pspecs);
        # only the dispatch in_specs see the wrapped form.
        self.adapters = adapters
        self._dispatch_pspecs = self._pspecs
        if adapters is not None:
            from picotron_tpu.inference import tenancy
            if adapters.dims != tenancy.adapter_dims(m):
                raise ValueError(
                    f"adapter pack built for dims {adapters.dims} but this "
                    f"model has {tenancy.adapter_dims(m)} — packs are "
                    f"model-shape specific")
            if adapters.rows != m.num_hidden_layers:
                raise ValueError(
                    f"adapter pack has {adapters.rows} layer rows; the "
                    f"serving stack holds {m.num_hidden_layers}")
            self._dispatch_pspecs = llama.adapter_pspecs(self._pspecs)
            self._adapter_sh = named_shardings(topo, {
                name: {"a": self._dispatch_pspecs["layers"][name]["a"],
                       "b": self._dispatch_pspecs["layers"][name]["b"]}
                for name in llama.QUANT_WEIGHT_LEAVES})
        # the decode-family dispatches shard their per-slot [B] operands
        # over dp — the adapter ids bound into the params tree ([L, B],
        # one row per GLOBAL slot) must shard with them, while one-shot
        # prefill (B=1, fully replicated) keeps the plain form
        self._decode_dispatch_pspecs = self._dispatch_pspecs
        if adapters is not None and self.dp_size > 1:
            layers = dict(self._dispatch_pspecs["layers"])
            for name in llama.QUANT_WEIGHT_LEAVES:
                layers[name] = {**layers[name], "ids": P("pp", "dp")}
            self._decode_dispatch_pspecs = {**self._dispatch_pspecs,
                                            "layers": layers}
        if self.paged is not None:
            self._cspecs = paged_kv.cache_pspecs(self.quantized,
                                                 policy=self.page_policy,
                                                 dp=self.dp_size)
        else:
            self._cspecs = kv_cache.cache_pspecs(self.quantized,
                                                 dp=self.dp_size)
        self._build_programs()
        # kv_cache.release works on both layouts (a paged release is the
        # same 1-element length write; the host manager frees the pages)
        # dp>1: pin cache-shaped outputs of the host-side helper jits to
        # the dp-sharded cache layout so donation round-trips never leave
        # a leaf gathered; dp=1 keeps them unconstrained (byte-identical
        # programs to the tp-only engine).
        cache_sh = (named_shardings(topo, self._cspecs)
                    if self.dp_size > 1 else None)
        self._release_jit = jax.jit(kv_cache.release, donate_argnums=(0,),
                                    out_shardings=cache_sh)
        if self.paged is not None:
            self._insert_jit = jax.jit(paged_kv.insert_prefill,
                                       donate_argnums=(0,),
                                       out_shardings=cache_sh)
            self._copy_page_jit = jax.jit(paged_kv.copy_page,
                                          donate_argnums=(0,),
                                          out_shardings=cache_sh)
            # page-transport device ops (inference/page_transport.py):
            # built ONCE here — a per-page jit build would recompile every
            # import (picolint PICO-J004's exact hazard). Export reads and
            # import writes are each ONE batched (pow-2-bucketed)
            # dispatch: an export pays one host sync however long the
            # prefix, and an import fault can only land BEFORE the
            # cache-donating dispatch, never mid-batch.
            self._slice_page_jit = jax.jit(paged_kv.slice_page)
            self._gather_pages_jit = jax.jit(paged_kv.gather_pages)
            self._write_pages_jit = jax.jit(paged_kv.write_pages,
                                            donate_argnums=(0,),
                                            out_shardings=cache_sh)
            self._set_length_jit = jax.jit(paged_kv.set_length,
                                           donate_argnums=(0,),
                                           out_shardings=cache_sh)
            self._init_cache_jit = jax.jit(
                partial(paged_kv.init_cache, m, self.slots, self.num_pages,
                        self.page_len, self.max_pages,
                        dtype=self.cache_dtype, quantized=self.quantized,
                        policy=self.page_policy),
                out_shardings=named_shardings(topo, self._cspecs))
        else:
            self._insert_jit = jax.jit(kv_cache.insert_prefill,
                                       donate_argnums=(0,),
                                       out_shardings=cache_sh)
            self._init_cache_jit = jax.jit(
                partial(kv_cache.init_cache, m, self.slots,
                        self.max_seq_len, dtype=self.cache_dtype,
                        quantized=self.quantized),
                out_shardings=named_shardings(topo, self._cspecs))

    def _build_programs(self) -> None:
        """(Re)build the compiled model programs. Runs at construction and
        again when the flash->dense degradation path flips ``attend_impl``:
        the kernel choice is a trace-time constant the jit wrappers close
        over, so changing it means new programs, not a runtime branch."""
        # one-shot prefill runs B=1 fully replicated across dp (every shard
        # computes the same slice; only the owner's insert consumes it), so
        # its kv output specs come from the dp-FREE base — identical to
        # self._cspecs when dp == 1
        base_cspecs = (paged_kv.cache_pspecs(self.quantized,
                                             policy=self.page_policy)
                       if self.paged is not None
                       else kv_cache.cache_pspecs(self.quantized))
        kv_spec = {n: s for n, s in base_cspecs.items()
                   if n not in paged_kv.META_LEAVES}
        mesh = self.topo.mesh
        # per-slot [B, ...] operands/outputs shard over dp (slot-major:
        # shard s owns global slots [s*spb, (s+1)*spb)); everything else
        # stays replicated. dp == 1 collapses dpP to P() — byte-identical
        # specs to the tp-only engine.
        dpP = P("dp") if self.dp_size > 1 else P()

        chunk_impl = (self._prefill_chunk_impl_paged
                      if self.kv_layout == "paged"
                      else self._prefill_chunk_impl)
        # the on-device sampling epilogue changes the programs' I/O: the
        # prefill family gains (key, temperature, top_k, top_p) inputs and
        # returns a sampled token id [1] where the host path returns [1, V]
        # logits; decode_step stops returning its [B, V] logits at all —
        # the whole point is that they never leave the device
        sod = self.sample_on_device
        samp = (P(), P(), P(), P()) if sod else ()
        # the return_hidden hook grows every program family by one
        # replicated [*, H] output (the residual stream is tp-replicated
        # after each layer's reduce) — a trace-time choice like the
        # sampling epilogue, so hidden-less engines compile byte-identical
        # programs
        hid = (P(),) if self.return_hidden else ()
        hidB = (dpP,) if self.return_hidden else ()
        self._prefill_jit = jax.jit(shard_map(
            self._prefill_impl, mesh,
            in_specs=(self._dispatch_pspecs, P(), P()) + samp,
            out_specs=(kv_spec, P()) + hid))
        self._prefill_chunk_jit = jax.jit(shard_map(
            chunk_impl, mesh,
            in_specs=(self._dispatch_pspecs, self._cspecs,
                      P(), P(), P(), P()) + samp,
            out_specs=(self._cspecs, P()) + hid),
            donate_argnums=(1,))
        self._decode_jit = jax.jit(shard_map(
            self._decode_impl, mesh,
            in_specs=(self._decode_dispatch_pspecs, self._cspecs,
                      dpP, P(), dpP, dpP, dpP),
            out_specs=((self._cspecs, dpP) if sod
                       else (self._cspecs, dpP, dpP)) + hidB),
            donate_argnums=(1,))
        self._decode_block_jit = self._make_decode_block_jit()
        self._decode_block_poison_jit = None  # chaos-only; built on demand
        self._verify_jit = None
        self._verify_poison_jit = None  # chaos-only; built on demand
        if self.spec_len > 0:
            self._verify_jit = self._make_verify_jit()
        # per-slot key schedule variants (key_schedule="slot"): same
        # programs with [B, 2] base keys folded per position IN-TRACE and
        # an extra next-token output the overlap pipeline carries on
        # device. jax.jit is lazy, but only the active schedule's
        # variants are referenced at all.
        self._decode_block_slot_jit = None
        self._decode_block_slot_poison_jit = None
        self._verify_slot_jit = None
        self._verify_slot_poison_jit = None
        if self.key_schedule == "slot":
            self._decode_block_slot_jit = self._make_decode_block_slot_jit()
            if self.spec_len > 0:
                self._verify_slot_jit = self._make_verify_slot_jit()
        # mixed prefill–decode dispatch variants (mixed_dispatch): the
        # slot-keyed programs + one fused prefill lane. Only built (and
        # only dispatched) on a mixed engine — a mixed-off engine's
        # program set stays byte-identical.
        self._decode_block_mixed_jit = None
        self._decode_block_mixed_poison_jit = None
        self._verify_mixed_jit = None
        self._verify_mixed_poison_jit = None
        if self.mixed:
            self._decode_block_mixed_jit = \
                self._make_decode_block_mixed_jit()
            if self.spec_len > 0:
                self._verify_mixed_jit = self._make_verify_mixed_jit()

    def _make_verify_jit(self, poison: bool = False):
        dpP = P("dp") if self.dp_size > 1 else P()
        hidB = (dpP,) if self.return_hidden else ()
        return jax.jit(shard_map(
            partial(self._verify_impl, poison=poison), self.topo.mesh,
            in_specs=(self._decode_dispatch_pspecs, self._cspecs,
                      dpP, dpP, P(), dpP, dpP, dpP, dpP, dpP),
            out_specs=(self._cspecs, dpP, dpP, dpP) + hidB),
            donate_argnums=(1,))

    def _verify_prog(self, poison: bool):
        """The verify executable to run (lazily builds the chaos
        NaN-poisoned variant)."""
        if self.mixed:
            if not poison:
                return self._verify_mixed_jit
            if self._verify_mixed_poison_jit is None:
                self._verify_mixed_poison_jit = self._make_verify_mixed_jit(
                    poison=True)
            return self._verify_mixed_poison_jit
        if self.key_schedule == "slot":
            if not poison:
                return self._verify_slot_jit
            if self._verify_slot_poison_jit is None:
                self._verify_slot_poison_jit = self._make_verify_slot_jit(
                    poison=True)
            return self._verify_slot_poison_jit
        if not poison:
            return self._verify_jit
        if self._verify_poison_jit is None:
            self._verify_poison_jit = self._make_verify_jit(poison=True)
        return self._verify_poison_jit

    def _make_verify_slot_jit(self, poison: bool = False):
        """Per-slot-key verify: base keys [B, 2] shard with their slots,
        and the program returns each row's post-round last token so the
        overlap pipeline can feed the next dispatch without a sync."""
        dpP = P("dp") if self.dp_size > 1 else P()
        hidB = (dpP,) if self.return_hidden else ()
        return jax.jit(shard_map(
            partial(self._verify_slot_impl, poison=poison), self.topo.mesh,
            in_specs=(self._decode_dispatch_pspecs, self._cspecs,
                      dpP, dpP, dpP, dpP, dpP, dpP, dpP, dpP),
            out_specs=(self._cspecs, dpP, dpP, dpP, dpP) + hidB),
            donate_argnums=(1,))

    def _make_decode_block_jit(self, poison: bool = False):
        dpP = P("dp") if self.dp_size > 1 else P()
        hidB = (dpP,) if self.return_hidden else ()
        return jax.jit(shard_map(
            partial(self._decode_block_impl, poison=poison), self.topo.mesh,
            in_specs=(self._decode_dispatch_pspecs, self._cspecs,
                      dpP, P(), dpP, dpP, dpP, dpP, dpP),
            out_specs=(self._cspecs, dpP, dpP) + hidB),
            donate_argnums=(1,))

    def _decode_block_prog(self, poison: bool):
        """The decode-block executable to run (lazily builds the chaos
        NaN-poisoned variant)."""
        if self.mixed:
            if not poison:
                return self._decode_block_mixed_jit
            if self._decode_block_mixed_poison_jit is None:
                self._decode_block_mixed_poison_jit = \
                    self._make_decode_block_mixed_jit(poison=True)
            return self._decode_block_mixed_poison_jit
        if self.key_schedule == "slot":
            if not poison:
                return self._decode_block_slot_jit
            if self._decode_block_slot_poison_jit is None:
                self._decode_block_slot_poison_jit = \
                    self._make_decode_block_slot_jit(poison=True)
            return self._decode_block_slot_poison_jit
        if not poison:
            return self._decode_block_jit
        if self._decode_block_poison_jit is None:
            self._decode_block_poison_jit = self._make_decode_block_jit(
                poison=True)
        return self._decode_block_poison_jit

    def _make_decode_block_slot_jit(self, poison: bool = False):
        """Per-slot-key decode block: [B, 2] base keys (sharded with
        their slots) replace the [block, 2] round keys; each scan step
        folds the live length in-trace, and the final carry token comes
        back as an extra output for the overlap pipeline."""
        dpP = P("dp") if self.dp_size > 1 else P()
        hidB = (dpP,) if self.return_hidden else ()
        return jax.jit(shard_map(
            partial(self._decode_block_slot_impl, poison=poison),
            self.topo.mesh,
            in_specs=(self._decode_dispatch_pspecs, self._cspecs,
                      dpP, dpP, dpP, dpP, dpP, dpP, dpP),
            out_specs=(self._cspecs, dpP, dpP, dpP) + hidB),
            donate_argnums=(1,))

    def _lane_specs(self):
        """(in_specs, out_specs) tails the prefill lane adds to a mixed
        program: every lane operand/output is a per-shard [dp, ...] row
        set, so they all shard over dp exactly like the per-slot batch
        operands (dp == 1 collapses to replicated)."""
        dpP = P("dp") if self.dp_size > 1 else P()
        lane_in = (dpP, dpP, dpP, dpP)  # tokens, slot, start, valid
        if self.sample_on_device:
            lane_in += (dpP, dpP, dpP, dpP)  # key, temp, top_k, top_p
        if self.adapters is not None:
            lane_in += (dpP,)
        lane_out = (dpP,) + ((dpP,) if self.return_hidden else ())
        return lane_in, lane_out

    def _make_decode_block_mixed_jit(self, poison: bool = False):
        """The fused decode-block + prefill-lane program
        (mixed_dispatch): the slot-keyed decode block's operands followed
        by the lane tail (``_lane_chunk``)."""
        dpP = P("dp") if self.dp_size > 1 else P()
        hidB = (dpP,) if self.return_hidden else ()
        lane_in, lane_out = self._lane_specs()
        return jax.jit(shard_map(
            partial(self._decode_block_mixed_impl, poison=poison),
            self.topo.mesh,
            in_specs=(self._decode_dispatch_pspecs, self._cspecs,
                      dpP, dpP, dpP, dpP, dpP, dpP, dpP) + lane_in,
            out_specs=(self._cspecs, dpP, dpP, dpP) + hidB + lane_out),
            donate_argnums=(1,))

    def _make_verify_mixed_jit(self, poison: bool = False):
        """The fused verify + prefill-lane program (mixed_dispatch)."""
        dpP = P("dp") if self.dp_size > 1 else P()
        hidB = (dpP,) if self.return_hidden else ()
        lane_in, lane_out = self._lane_specs()
        return jax.jit(shard_map(
            partial(self._verify_mixed_impl, poison=poison),
            self.topo.mesh,
            in_specs=(self._decode_dispatch_pspecs, self._cspecs,
                      dpP, dpP, dpP, dpP, dpP, dpP, dpP, dpP) + lane_in,
            out_specs=(self._cspecs, dpP, dpP, dpP, dpP) + hidB
            + lane_out),
            donate_argnums=(1,))

    # ---- dispatch hooks + graceful degradation ----------------------------

    def _hook(self, kind: str, budget=None) -> None:
        """Fire the before-dispatch hook with the active slot indices
        (``budget > 0`` rows; dispatches without a budget report none)
        and count the dispatch in the metrics registry. When a
        ClusterMonitor is attached (``attach_monitor`` — multi-host dp
        serving), every dispatch first checks peer leases: a dead dp peer
        means the collective about to run would wedge forever, so the
        monitor's exit path fires instead (exit 77 under the default
        exit_fn — the supervisor's restart signal)."""
        self._check_monitor()
        self.obs.registry.counter(
            "picotron_dispatch_total",
            "engine dispatches by kind", kind=kind).inc()
        if self.hooks is None:
            return
        slots = ([] if budget is None
                 else np.flatnonzero(np.asarray(budget) > 0).tolist())
        self.hooks.before_dispatch(kind, slots)

    def attach_monitor(self, monitor) -> None:
        """Attach a ``resilience.cluster.ClusterMonitor`` lease guard:
        every subsequent dispatch (and every migration's donating write)
        first checks peer leases, so a dead dp peer takes the monitor's
        exit path — exit 77 under the default exit_fn — instead of
        wedging this host inside the dispatch collective forever."""
        self.monitor = monitor

    def _check_monitor(self) -> None:
        if self.monitor is not None:
            dead = self.monitor.check_peers()
            if dead is not None:
                self.monitor._exit(*dead)

    def observe_dispatch(self, kind: str, seconds: float,
                         host_sync_s: Optional[float] = None) -> None:
        """Record one dispatch's end-to-end wall time (submit through the
        caller's host sync) into the registry. Callers that pay the sync
        — the batcher's round closures, the benches — report here; the
        engine itself never blocks on its own async dispatches just to
        time them."""
        reg = self.obs.registry
        reg.histogram("picotron_dispatch_seconds",
                      "dispatch wall time incl. host sync, by kind",
                      kind=kind).observe(seconds)
        if host_sync_s is not None:
            reg.histogram("picotron_host_sync_seconds",
                          "host blocked on device results, by kind",
                          kind=kind).observe(host_sync_s)

    def _poison(self, kind: str) -> bool:
        return self.hooks is not None and self.hooks.poison_logits(kind)

    def _flash_fallback(self, exc: Exception) -> bool:
        """Degrade flash->dense after a failed dispatch: latch the process
        flag, log once, rebuild the compiled programs on dense. Returns
        whether the caller should re-dispatch."""
        if (self.attend_impl != "flash"
                or not self.cfg.inference.attend_fallback):
            return False
        global _FLASH_BROKEN
        if not _FLASH_BROKEN:
            _FLASH_BROKEN = True
            log0(f"attend_impl 'flash' failed at dispatch "
                 f"({type(exc).__name__}: {exc}); falling back to 'dense' "
                 f"for the rest of the process", flush=True)
        self.attend_impl = self.cfg.inference.attend_impl = "dense"
        self._build_programs()
        return True

    def _dispatch(self, call):
        """Run one compiled cache dispatch. A flash failure rebuilds on
        dense and re-dispatches once (``call`` must re-read the jit
        attribute, not capture the object). The re-dispatch is sound when
        the failure predates execution (trace/compile — where flash breaks
        off-TPU); a failure AFTER the donated cache was consumed makes the
        retry fail fast on the deleted buffers, which lands in the
        batcher's slot-recovery path instead of wedging."""
        try:
            return call()
        except Exception as e:  # noqa: BLE001 - rethrown unless degrading
            if self._flash_fallback(e):
                return call()
            raise

    # ---- model programs (run inside shard_map; tp axis collectives live) --

    def _pack_kv(self, K, V):
        """Prefill K/V blocks in cache storage form: quantize (int8 mode)
        or cast to the cache dtype. hot_bf16 policy engines pack BOTH
        representations (full precision + int8 with scales) — the paged
        insert parks them side by side, the per-page flag picks the read."""
        if self.quantized:
            qk, ks = kv_cache.quantize_kv(K)
            qv, vs = kv_cache.quantize_kv(V)
            return {"k": qk, "v": qv, "k_scale": ks, "v_scale": vs}
        out = {"k": K.astype(self.cache_dtype),
               "v": V.astype(self.cache_dtype)}
        if self.page_policy:
            qk, ks = kv_cache.quantize_kv(K)
            qv, vs = kv_cache.quantize_kv(V)
            out.update({"k_q": qk, "v_q": qv, "k_scale": ks, "v_scale": vs})
        return out

    def _epilogue(self, logits, key, temperature, top_k, top_p):
        """The fused on-device sampling epilogue: sanitize -> temperature
        -> top-k -> top-p -> categorical (sampling.sample's fused filter,
        exactly the host sampler's pipeline over the same key), collapsing
        the dispatch's host-bound payload from [B, V] fp32 logits to [B]
        int32 token ids."""
        return sampling.sample(logits, key, temperature, top_k, top_p)

    def _prefill_impl(self, params, tokens, length, *sample):
        """tokens [1, S_bucket] int32, length [1] -> (kv blocks, last-token
        logits [1, V]). Pad tokens beyond ``length`` produce K/V rows the
        length mask makes unreachable. With the on-device sampling
        epilogue, ``sample`` is (key, temperature [1], top_k [1],
        top_p [1]) and the second return is the sampled token id [1]
        int32 — the logits never leave the device."""
        cfg = self.cfg
        S = tokens.shape[1]
        cos_l = lax.dynamic_slice_in_dim(self._cos, 0, S, 0)
        sin_l = lax.dynamic_slice_in_dim(self._sin, 0, S, 0)
        h = llama.embed_lookup(params["embed"], tokens).astype(self._dt)

        def body(hc, lp):
            hc, kv = llama.decoder_layer(lp, hc, cos_l, sin_l, cfg,
                                         return_kv=True)
            return hc, kv

        h, (K, V) = lax.scan(body, h, params["layers"])
        # only the last real token's logits are consumed: slice its hidden
        # row BEFORE the LM-head matmul and the vocab tp-gather, so the
        # bucket pays one [1, H] @ [H, V] row instead of S_bucket of them
        h_last = jnp.take_along_axis(h, (length - 1)[:, None, None], axis=1)
        last = tp_gather(llama.head_logits(params, h_last, cfg))[:, 0]
        last = last.astype(jnp.float32)
        out = self._epilogue(last, *sample) if self.sample_on_device \
            else last
        if self.return_hidden:
            return self._pack_kv(K, V), out, h_last[:, 0]
        return self._pack_kv(K, V), out

    def _split_cache(self, cache):
        """(per-layer K/V leaves to scan, lengths) — the scan consumes every
        [L, ...] cache leaf the way it consumes the stacked params. The
        paged layout's ``block_tables`` (and the hot_bf16 policy's
        ``page_quant`` flags) have no layer axis: they ride as scan
        constants, injected per layer by ``_layer_body``."""
        return ({n: a for n, a in cache.items()
                 if n not in paged_kv.META_LEAVES},
                cache["lengths"])

    def _meta(self, cache) -> dict:
        """The layer-less host-owned metadata leaves a paged cache carries
        (block tables; page_quant under the hot_bf16 policy)."""
        return {n: cache[n] for n in ("block_tables", "page_quant")
                if n in cache}

    def _local_meta(self, cache) -> dict:
        """``_meta`` for use INSIDE a shard_map trace: with dp > 1 the page
        pool arrives shard-local (pages_per_shard pages, local page 0 =
        this shard's NULL page) while block tables carry GLOBAL page ids
        (shard s owns [s*pps, (s+1)*pps)), so subtract this shard's base —
        a slot's own entries localize into range, its NULL entries localize
        to 0. ``_rebuild`` keeps the ORIGINAL global tables; this view is
        read-only. dp == 1 is the identity."""
        meta = self._meta(cache)
        if self.dp_size > 1 and "block_tables" in meta:
            base = (lax.axis_index("dp").astype(jnp.int32)
                    * self.pages_per_shard)
            meta = {**meta, "block_tables": meta["block_tables"] - base}
        return meta

    def _slot_owner(self, slot):
        """Owner gating for single-slot programs under dp sharding: map a
        GLOBAL slot id to (local slot, is_owner) on the executing shard.
        Non-owner shards clip to a valid local index so slicing stays in
        bounds; their compute is discarded (writes where'd out, logits
        psum-masked). dp == 1 returns the slot unchanged with owner
        None (no gating)."""
        if self.dp_size <= 1:
            return slot, None
        shard = lax.axis_index("dp").astype(jnp.int32)
        loc = jnp.asarray(slot, jnp.int32) - shard * self.slots_per_shard
        is_owner = (loc >= 0) & (loc < self.slots_per_shard)
        return jnp.clip(loc, 0, self.slots_per_shard - 1), is_owner

    def _owner_reduce(self, x, owner):
        """Make a single-slot program output replicated across dp shards:
        the owner contributes its value, the rest contribute zeros, one
        psum agrees everywhere (where-select, not multiply, so non-owner
        garbage — even NaN — never reaches the sum). This is the ONLY dp
        collective in the serving programs, and it lives on the chunked
        prefill path alone; decode_block/verify stay collective-free.
        dp == 1 (owner None) is the identity."""
        if owner is None:
            return x
        return comm_trace.log(
            "prefill_owner_reduce", "dp",
            lax.psum(jnp.where(owner, x, jnp.zeros_like(x)), "dp"))

    def _layer_body(self, cos_b, sin_b, pos, meta):
        """Build the layer-scan body: decode one layer against its cache
        leaves. For paged caches the (layer-less) metadata leaves are
        spliced into each layer's dict on the way in —
        kv_cache.cache_write/attend dispatch on their presence — and
        stripped on the way out so the scan stacks only real [L, ...]
        leaves."""

        def body(hc, xs):
            lp, lc = xs
            if meta:
                lc = {**lc, **meta}
            hc, lc = llama.decoder_layer(lp, hc, cos_b, sin_b, self.cfg,
                                         cache=lc, pos=pos)
            if meta:
                lc = {n: a for n, a in lc.items() if n not in meta}
            return hc, lc

        return body

    def _rebuild(self, cache, new_leaves, lengths):
        """Reassemble a cache pytree from updated per-layer leaves +
        lengths, carrying the paged layout's metadata leaves through
        unchanged (the HOST allocator owns them; device programs only
        read)."""
        return {**new_leaves, **self._meta(cache), "lengths": lengths}

    def _model_block(self, params, cache, tokens, rows, pos,
                     extra_meta=None):
        """The shared incremental-decode model body: embed ``tokens``
        [B, S] at RoPE positions ``rows`` [B, S], scan the layer stack
        writing each slot's S new K/V rows from ``pos`` [B]
        (kv_cache.cache_write), attend causally over cache prefix + block,
        and return (updated per-layer leaves, logits [B, S, V] fp32,
        pre-final-norm hidden states [B, S, H]). S == 1 is the decode
        step; S > 1 the speculative verify block. ``extra_meta`` rides
        into each layer's cache dict alongside the paged metadata (the
        ragged verify's ``draft_valid`` write mask). Lengths are NOT
        advanced here — callers apply their own activity rule."""
        cos_b, sin_b = rope_at_positions(self._cos, self._sin, rows)
        h = llama.embed_lookup(params["embed"], tokens).astype(self._dt)
        leaves, _ = self._split_cache(cache)
        meta = self._local_meta(cache)
        if extra_meta:
            meta = {**meta, **extra_meta}
        body = self._layer_body(cos_b, sin_b, pos, meta)
        h, new_leaves = lax.scan(body, h, (params["layers"], leaves))
        logits = tp_gather(llama.head_logits(params, h, self.cfg))
        return new_leaves, logits.astype(jnp.float32), h

    def _decode_core(self, params, cache, tokens):
        """One model step for all slots: ``tokens`` [B] at each slot's own
        ``cache['lengths']`` position -> (updated per-layer leaves,
        logits [B, V] fp32, hidden [B, H])."""
        pos = cache["lengths"]  # [B] write index of the incoming token
        new_leaves, logits, h = self._model_block(
            params, cache, tokens[:, None], pos[:, None], pos)
        return new_leaves, logits[:, 0], h[:, 0]

    def _decode_impl(self, params, cache, tokens, key, temperature,
                     top_k, top_p):
        """One autoregressive step for all slots: tokens [B] (each slot's
        current last token), cache lengths give every slot its position.
        Sampling always runs on device; with the epilogue enabled the
        [B, V] logits are additionally DROPPED from the outputs, so the
        dispatch's host payload is the [B] token ids alone. A
        ``return_hidden`` engine appends the step's pre-final-norm hidden
        states [B, H] — the learned drafter's input."""
        pos = cache["lengths"]
        new_leaves, logits, h = self._decode_core(params, cache, tokens)
        next_tok = sampling.sample(logits, key, temperature, top_k, top_p)
        # free slots (length 0) ride along for shape stability but stay at
        # length 0 — their row-0 writes are never visible
        new_cache = self._rebuild(cache, new_leaves,
                                  jnp.where(pos > 0, pos + 1, 0))
        out = ((new_cache, next_tok) if self.sample_on_device
               else (new_cache, next_tok, logits))
        return out + (h,) if self.return_hidden else out

    def _decode_block_impl(self, params, cache, tokens, keys, eos_id,
                           budget, temperature, top_k, top_p,
                           poison=False):
        """``decode_block_len`` autoregressive steps in one program.

        tokens [B] (each slot's current last token), keys [block_len, 2]
        (one PRNG key per in-block step — the host's per-round split chain,
        so block_len == 1 reproduces the per-token loop bit-for-bit),
        eos_id [B] int32 (−1 = none), budget [B] int32 remaining tokens.
        A slot is active while it has a parked sequence AND budget; hitting
        EOS zeroes its budget. Inactive slots emit pad token 0, stop
        advancing their cache length, and their (recomputed) row writes
        land beyond the length mask — invisible, exactly like the free
        slots that already ride through the single-step program.

        Returns (cache, tokens [B, block_len], counts [B]): ``counts[b]``
        leading entries of row b are the tokens slot b actually produced.

        ``poison`` (trace-time, chaos only) replaces every step's logits
        with NaN — the build that proves the sampler's non-finite gate
        keeps emitting defined tokens, the exact counterpart of
        train_step's ``poison_nonfinite``.

        A ``return_hidden`` engine also returns hidden [B, H]: each
        slot's pre-final-norm hidden state at its LAST active step — the
        position whose logits produced the slot's final emitted token,
        exactly what the learned drafter needs to draft its continuation.
        """
        rh = self.return_hidden
        hid0 = jnp.zeros((tokens.shape[0], self.cfg.model.hidden_size),
                         self._dt)

        def step(carry, key_t):
            cache, tok, budget, hid = carry
            pos = cache["lengths"]
            active = (pos > 0) & (budget > 0)
            new_leaves, logits, h = self._decode_core(params, cache, tok)
            if poison:
                logits = jnp.full_like(logits, jnp.nan)
            sampled = sampling.sample(logits, key_t, temperature,
                                      top_k, top_p)
            emit = jnp.where(active, sampled, 0)
            new_budget = jnp.where(active, budget - 1, budget)
            hit_eos = active & (eos_id >= 0) & (sampled == eos_id)
            new_budget = jnp.where(hit_eos, 0, new_budget)
            new_cache = self._rebuild(cache, new_leaves,
                                      jnp.where(active, pos + 1, pos))
            next_tok = jnp.where(active, sampled, tok)
            new_hid = jnp.where(active[:, None], h, hid) if rh else hid
            return (new_cache, next_tok, new_budget, new_hid), (emit, active)

        (cache, _, _, hid), (toks, actives) = lax.scan(
            step, (cache, tokens, budget, hid0), keys)
        out = (cache, jnp.swapaxes(toks, 0, 1),
               jnp.sum(actives.astype(jnp.int32), axis=0))
        return out + (hid,) if rh else out

    def _verify_impl(self, params, cache, tokens, valid, key, eos_id,
                     budget, temperature, top_k, top_p, poison=False):
        """The speculative verify pass: tokens [B, S] (S = spec_len + 1 —
        each slot's current last token followed by its spec_len drafted
        continuation tokens), scored in ONE model dispatch. ``valid`` [B]
        int32 is each slot's count of REAL fed tokens (its draft length
        + 1) — the RAGGED hook: the compiled shape stays [B, spec_len+1]
        while each slot speculates at its own controller-chosen length
        (pad columns past ``valid`` are forced rejections in the accept
        rule and masked out of the K/V write — kv_cache.cache_write's
        ``draft_valid``); ``valid == S`` everywhere reproduces the
        fixed-length verify bit for bit.

        All S positions embed at each slot's own offsets
        (``cache['lengths'] + 0..S-1``), their K/V are written into the
        slot OPTIMISTICALLY (the batched-write branch of
        kv_cache.cache_write; int8 caches quantize on write), and
        attention runs causally over the cache prefix plus the fed block —
        the same masked kernel the chunked prefill uses, batched over
        slots. The resulting logits[b, i] score the token FOLLOWING fed
        token i, so ``sampling.speculative_accept`` can accept the
        matching draft prefix and draw the one fresh token, all on device.

        Rollback is the length pointer: ``lengths`` advances by the
        emitted count only (accepted prefix + the fresh token's slot-feed
        position), so rejected draft rows — already written — sit beyond
        the mask, stale and unreachable, and the next dispatch overwrites
        them. EOS truncates the emitted run on device (the stream ends AT
        the first emitted EOS); ``budget`` [B] caps it exactly like
        decode_block's budget. Free slots (length 0) ride along inactive:
        they emit count 0 and their length stays 0.

        Returns (cache, emitted [B, S], counts [B], accepted [B]) where
        ``accepted`` is the number of DRAFT tokens that made it into the
        emitted stream (the accept-rate numerator). A ``return_hidden``
        engine appends hidden [B, H]: each slot's pre-final-norm hidden
        state at the position whose logits produced its final emitted
        token (row ``counts - 1``) — the learned drafter's next input.
        """
        B, S = tokens.shape
        pos0 = cache["lengths"]
        rows = pos0[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
        new_leaves, logits, h = self._model_block(
            params, cache, tokens, rows, pos0,
            extra_meta={"draft_valid": valid})  # logits [B, S, V]
        if poison:
            # chaos only (trace-time): the build that proves
            # speculative_accept's sanitized argmax keeps the emitted
            # stream defined — decode_block's ``poison`` counterpart
            logits = jnp.full_like(logits, jnp.nan)
        emitted, counts = sampling.speculative_accept(
            logits, tokens[:, 1:], key, temperature, top_k, top_p,
            draft_len=valid - 1)
        raw = counts  # pre-clip: accepted drafts + 1 fresh token
        active = (pos0 > 0) & (budget > 0)
        counts = jnp.where(active, jnp.minimum(counts, budget), 0)
        cols = jnp.arange(S, dtype=jnp.int32)[None, :]
        is_eos = ((eos_id >= 0)[:, None] & (emitted == eos_id[:, None])
                  & (cols < counts[:, None]))
        counts = jnp.where(jnp.any(is_eos, axis=1),
                           jnp.argmax(is_eos, axis=1) + 1, counts)
        emitted = jnp.where(cols < counts[:, None], emitted, 0)
        # of the emitted run, all but (possibly) the last token are drafts:
        # when nothing clipped, raw - 1 drafts + 1 fresh; when EOS/budget
        # clipped below that, every emitted token was a draft
        accepted = jnp.minimum(raw - 1, counts)
        new_cache = self._rebuild(cache, new_leaves,
                                  jnp.where(active, pos0 + counts, pos0))
        out = (new_cache, emitted, counts, accepted)
        if not self.return_hidden:
            return out
        # the last emitted token (greedy: == argmax over this row's
        # logits) came from row counts - 1; clip covers inactive rows
        idx = jnp.clip(counts - 1, 0, S - 1)[:, None, None]
        return out + (jnp.take_along_axis(h, idx, axis=1)[:, 0],)

    def _decode_block_slot_impl(self, params, cache, tokens, base_keys,
                                eos_id, budget, temperature, top_k, top_p,
                                poison=False):
        """``_decode_block_impl`` under the per-slot key schedule: instead
        of one shared key per in-block step, every row's draw at pre-step
        length ℓ uses ``fold_in(base_keys[b], ℓ)`` — the key that position
        owns no matter how steps are grouped into rounds, which is the
        invariant the overlap pipeline's bit-identity rests on
        (docs/INFERENCE.md "Overlapped scheduling"). Also returns the
        final carry token [B] (each slot's post-block last token, the
        input token where a slot never ran) so the lookahead dispatch can
        consume it without a host sync."""
        rh = self.return_hidden
        hid0 = jnp.zeros((tokens.shape[0], self.cfg.model.hidden_size),
                         self._dt)

        def step(carry, _):
            cache, tok, budget, hid = carry
            pos = cache["lengths"]
            active = (pos > 0) & (budget > 0)
            keys = jax.vmap(jax.random.fold_in)(base_keys, pos)
            new_leaves, logits, h = self._decode_core(params, cache, tok)
            if poison:
                logits = jnp.full_like(logits, jnp.nan)
            sampled = sampling.sample_rowkeys(logits, keys, temperature,
                                              top_k, top_p)
            emit = jnp.where(active, sampled, 0)
            new_budget = jnp.where(active, budget - 1, budget)
            hit_eos = active & (eos_id >= 0) & (sampled == eos_id)
            new_budget = jnp.where(hit_eos, 0, new_budget)
            new_cache = self._rebuild(cache, new_leaves,
                                      jnp.where(active, pos + 1, pos))
            next_tok = jnp.where(active, sampled, tok)
            new_hid = jnp.where(active[:, None], h, hid) if rh else hid
            return (new_cache, next_tok, new_budget, new_hid), (emit, active)

        (cache, tok, _, hid), (toks, actives) = lax.scan(
            step, (cache, tokens, budget, hid0), None,
            length=self.decode_block_len)
        out = (cache, jnp.swapaxes(toks, 0, 1),
               jnp.sum(actives.astype(jnp.int32), axis=0), tok)
        return out + (hid,) if rh else out

    def _verify_slot_impl(self, params, cache, tokens, valid, base_keys,
                          eos_id, budget, temperature, top_k, top_p,
                          poison=False):
        """``_verify_impl`` under the per-slot key schedule: acceptance is
        sample-and-match (sampling.speculative_match) — the program draws
        the target chain's own token at every fed position with that
        position's folded key and accepts the matching draft prefix, so
        the emitted stream never depends on the draft VALUES and equals
        the per-position decode chain bit for bit (the property that lets
        the overlap pipeline verify against one-round-stale drafts).
        Returns an extra next-token output [B]: the last emitted token
        where the row ran, else the fed last token."""
        B, S = tokens.shape
        pos0 = cache["lengths"]
        rows = pos0[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
        new_leaves, logits, h = self._model_block(
            params, cache, tokens, rows, pos0,
            extra_meta={"draft_valid": valid})  # logits [B, S, V]
        if poison:
            logits = jnp.full_like(logits, jnp.nan)
        # rows[b, i] is exactly the fold_in data the non-speculative chain
        # uses for the token following fed token i (its pre-step length)
        emitted, counts = sampling.speculative_match(
            logits, tokens[:, 1:], base_keys, rows, temperature,
            top_k, top_p, draft_len=valid - 1)
        raw = counts
        active = (pos0 > 0) & (budget > 0)
        counts = jnp.where(active, jnp.minimum(counts, budget), 0)
        cols = jnp.arange(S, dtype=jnp.int32)[None, :]
        is_eos = ((eos_id >= 0)[:, None] & (emitted == eos_id[:, None])
                  & (cols < counts[:, None]))
        counts = jnp.where(jnp.any(is_eos, axis=1),
                           jnp.argmax(is_eos, axis=1) + 1, counts)
        emitted = jnp.where(cols < counts[:, None], emitted, 0)
        accepted = jnp.minimum(raw - 1, counts)
        new_cache = self._rebuild(cache, new_leaves,
                                  jnp.where(active, pos0 + counts, pos0))
        last_idx = jnp.clip(counts - 1, 0, S - 1)[:, None]
        next_tok = jnp.where(
            counts > 0,
            jnp.take_along_axis(emitted, last_idx, axis=1)[:, 0],
            tokens[:, 0])
        out = (new_cache, emitted, counts, accepted, next_tok)
        if not self.return_hidden:
            return out
        idx = jnp.clip(counts - 1, 0, S - 1)[:, None, None]
        return out + (jnp.take_along_axis(h, idx, axis=1)[:, 0],)

    def _prefill_chunk_impl(self, params, cache, tokens, slot, start, valid,
                            *sample):
        """One fixed-width prefill chunk for one slot: tokens [1, C] (pad
        past ``valid``), written into the cache at rows
        [start, start + C) of ``slot``. Queries attend causally over the
        already-written prefix plus the chunk (decode_attention with
        S = C); pad queries' outputs and their K/V rows beyond
        ``start + valid`` sit past the final length — unreachable. Returns
        (cache with lengths[slot] = start + valid, the last valid token's
        logits [1, V] fp32 — consumed by the caller on the final chunk).
        With the on-device epilogue, ``sample`` is (key, temperature,
        top_k, top_p) and the second return is the sampled token [1]
        int32 instead — every chunk samples from the SAME key (cheap next
        to the model body) and only the final chunk's draw is consumed,
        so no key is ever burned on an intermediate chunk."""
        cfg = self.cfg
        C = tokens.shape[1]
        start = jnp.asarray(start, jnp.int32)
        pos_rows = (start + jnp.arange(C, dtype=jnp.int32))[None, :]  # [1,C]
        cos_b, sin_b = rope_at_positions(self._cos, self._sin, pos_rows)
        h = llama.embed_lookup(params["embed"], tokens).astype(self._dt)
        leaves, lengths = self._split_cache(cache)
        pos = jnp.full((1,), start, jnp.int32)
        # dp > 1: every shard traces the same chunk, but only the slot's
        # owner keeps its writes — non-owners slice a clipped local slot,
        # discard the updated rows (write-back of the unchanged slice is a
        # no-op), and contribute zeros to the logits psum below
        loc, owner = self._slot_owner(slot)

        def body(hc, xs):
            lp, lc = xs
            # this slot's [1, T, ...] block rows, updated then scattered back
            slot_c = {n: lax.dynamic_slice_in_dim(a, loc, 1, axis=0)
                      for n, a in lc.items()}
            hc, slot_new = llama.decoder_layer(lp, hc, cos_b, sin_b, cfg,
                                               cache=slot_c, pos=pos)
            if owner is not None:
                slot_new = {n: jnp.where(owner, slot_new[n], slot_c[n])
                            for n in slot_new}
            lc = {n: lax.dynamic_update_slice_in_dim(lc[n], slot_new[n],
                                                     loc, axis=0)
                  for n in lc}
            return hc, lc

        h, new_leaves = lax.scan(body, h, (params["layers"], leaves))
        idx = jnp.clip(valid - 1, 0, C - 1)
        h_last = jnp.take_along_axis(
            h, jnp.full((1, 1, 1), idx, jnp.int32), axis=1)
        last = tp_gather(llama.head_logits(params, h_last, cfg))[:, 0]
        last = self._owner_reduce(last.astype(jnp.float32), owner)
        new_lengths = lengths.at[loc].set(start + valid)
        if owner is not None:
            new_lengths = jnp.where(owner, new_lengths, lengths)
        new_cache = {**new_leaves, "lengths": new_lengths}
        out = self._epilogue(last, *sample) if self.sample_on_device \
            else last
        if self.return_hidden:
            return new_cache, out, self._owner_reduce(h_last[:, 0], owner)
        return new_cache, out

    def _prefill_chunk_impl_paged(self, params, cache, tokens, slot, start,
                                  valid, *sample):
        """Paged counterpart of ``_prefill_chunk_impl``: the slot's pages
        cannot be sliced out as a contiguous block, so the layer scan runs
        against the whole pool with the slot's block-table row (B = 1) —
        writes scatter through the row, attention gathers/walks it. Also
        the prefix-sharing resume path: with ``start`` past a cached
        prefix, the chunk attends over SHARED pages it never computed."""
        cfg = self.cfg
        C = tokens.shape[1]
        start = jnp.asarray(start, jnp.int32)
        pos_rows = (start + jnp.arange(C, dtype=jnp.int32))[None, :]
        cos_b, sin_b = rope_at_positions(self._cos, self._sin, pos_rows)
        h = llama.embed_lookup(params["embed"], tokens).astype(self._dt)
        leaves, lengths = self._split_cache(cache)
        # dp > 1: non-owner shards force their (clipped) table row to the
        # local NULL page — their chunk writes scribble the shard's
        # designated scratch page and their reads never feed the result
        # (logits psum-masked below, write-back of pool pages goes through
        # the row, and lengths stay untouched)
        loc, owner = self._slot_owner(slot)
        local_meta = self._local_meta(cache)
        row = lax.dynamic_slice_in_dim(local_meta["block_tables"], loc, 1,
                                       axis=0)  # [1, max_pages]
        if owner is not None:
            row = jnp.where(owner, row, jnp.zeros_like(row))
        pos = jnp.full((1,), start, jnp.int32)
        meta = {**local_meta, "block_tables": row}
        body = self._layer_body(cos_b, sin_b, pos, meta)
        h, new_leaves = lax.scan(body, h, (params["layers"], leaves))
        idx = jnp.clip(valid - 1, 0, C - 1)
        h_last = jnp.take_along_axis(
            h, jnp.full((1, 1, 1), idx, jnp.int32), axis=1)
        last = tp_gather(llama.head_logits(params, h_last, cfg))[:, 0]
        last = self._owner_reduce(last.astype(jnp.float32), owner)
        new_lengths = lengths.at[loc].set(start + valid)
        if owner is not None:
            new_lengths = jnp.where(owner, new_lengths, lengths)
        new_cache = self._rebuild(cache, new_leaves, new_lengths)
        out = self._epilogue(last, *sample) if self.sample_on_device \
            else last
        if self.return_hidden:
            return new_cache, out, self._owner_reduce(h_last[:, 0], owner)
        return new_cache, out

    def _lane_chunk(self, params, cache, tokens, slot, start, valid, *rest):
        """The fused prefill LANE: one fixed-width chunk for one slot per
        dp shard, run on the cache the SAME dispatch's decode half just
        updated. All operands arrive shard-local ([1, ...] rows of the
        [dp, ...] host arrays): tokens [1, C], slot [1] (LOCAL slot
        index, clipped-valid when idle), start [1] (the chunk's first
        write row — the contiguous window slide / paged absolute start,
        exactly ``prefill_chunked``'s convention), valid [1] (real token
        count; 0 = idle lane). ``rest`` carries (key [1, 2], temperature
        [1], top_k [1], top_p [1]) on a sample_on_device engine and the
        lane's adapter id [1] on a tenancy engine.

        The body IS the serial chunk program's: same B = 1 slot view,
        same batched-scatter cache_write, same ``pos_q = start + s``
        rows, same last-valid-token head slice, same epilogue from the
        same key — so every K/V byte and every logit bit matches what a
        separate ``prefill_chunked`` dispatch would have produced. An
        idle lane still traces (shape stability = one compile): its
        writes are where'd out (contiguous) or land on this shard's NULL
        scratch page (paged), its lengths stay untouched, and its
        sampled token is garbage the host discards. Unlike the serial
        chunk program there is NO dp owner psum — each shard runs its
        OWN lane and keeps its result in its [dp] output row."""
        cfg = self.cfg
        rest = list(rest)
        sample = ()
        if self.sample_on_device:
            key, s_temp, s_topk, s_topp = rest[:4]
            rest = rest[4:]
            sample = (key[0], s_temp, s_topk, s_topp)
        C = tokens.shape[1]
        slot_i = jnp.asarray(slot[0], jnp.int32)
        start_i = jnp.asarray(start[0], jnp.int32)
        valid_i = jnp.asarray(valid[0], jnp.int32)
        active = valid_i > 0
        lane_params = params
        if self.adapters is not None:
            # the decode binding carried per-slot ids [L, local slots];
            # the lane's B = 1 compute needs ITS row — rebind in-trace
            # (same {"w","a","b","ids"} leaf form the serial chunk
            # dispatch binds host-side)
            adapter = rest[0]
            L = cfg.model.num_hidden_layers
            ids1 = jnp.broadcast_to(
                jnp.asarray(adapter, jnp.int32)[None, :], (L, 1))
            layers = dict(params["layers"])
            for name in llama.QUANT_WEIGHT_LEAVES:
                layers[name] = {**layers[name], "ids": ids1}
            lane_params = {**params, "layers": layers}
        pos_rows = (start_i + jnp.arange(C, dtype=jnp.int32))[None, :]
        cos_b, sin_b = rope_at_positions(self._cos, self._sin, pos_rows)
        h = llama.embed_lookup(lane_params["embed"],
                               tokens).astype(self._dt)
        leaves, lengths = self._split_cache(cache)
        pos = jnp.full((1,), start_i, jnp.int32)
        if self.kv_layout == "paged":
            local_meta = self._local_meta(cache)
            row = lax.dynamic_slice_in_dim(local_meta["block_tables"],
                                           slot_i, 1, axis=0)
            # idle lane scribbles this shard's NULL scratch page
            row = jnp.where(active, row, jnp.zeros_like(row))
            meta = {**local_meta, "block_tables": row}
            body = self._layer_body(cos_b, sin_b, pos, meta)
            h, new_leaves = lax.scan(body, h,
                                     (lane_params["layers"], leaves))
        else:
            def body(hc, xs):
                lp, lc = xs
                slot_c = {n: lax.dynamic_slice_in_dim(a, slot_i, 1, axis=0)
                          for n, a in lc.items()}
                hc, slot_new = llama.decoder_layer(lp, hc, cos_b, sin_b,
                                                   cfg, cache=slot_c,
                                                   pos=pos)
                slot_new = {n: jnp.where(active, slot_new[n], slot_c[n])
                            for n in slot_new}
                lc = {n: lax.dynamic_update_slice_in_dim(
                    lc[n], slot_new[n], slot_i, axis=0) for n in lc}
                return hc, lc

            h, new_leaves = lax.scan(body, h,
                                     (lane_params["layers"], leaves))
        idx = jnp.clip(valid_i - 1, 0, C - 1)
        h_last = jnp.take_along_axis(
            h, jnp.full((1, 1, 1), idx, jnp.int32), axis=1)
        last = tp_gather(llama.head_logits(lane_params, h_last, cfg))[:, 0]
        last = last.astype(jnp.float32)
        new_lengths = jnp.where(active,
                                lengths.at[slot_i].set(start_i + valid_i),
                                lengths)
        new_cache = self._rebuild(cache, new_leaves, new_lengths)
        out = self._epilogue(last, *sample) if self.sample_on_device \
            else last
        if self.return_hidden:
            return new_cache, out, h_last[:, 0]
        return new_cache, out

    def _decode_block_mixed_impl(self, params, cache, tokens, base_keys,
                                 eos_id, budget, temperature, top_k,
                                 top_p, *lane, poison=False):
        """``_decode_block_slot_impl`` + one prefill lane in the SAME
        program: the decode half runs first (the lane slot rides through
        it inactive — budget 0, so its ghost row lands at its current
        length and the lane immediately overwrites it), then the lane
        chunk advances on the updated cache. Appends the lane outputs
        (sampled token / logits row[, lane hidden]) after the decode
        family's."""
        d = self._decode_block_slot_impl(
            params, cache, tokens, base_keys, eos_id, budget,
            temperature, top_k, top_p, poison=poison)
        ln = self._lane_chunk(params, d[0], *lane)
        return (ln[0],) + d[1:] + ln[1:]

    def _verify_mixed_impl(self, params, cache, tokens, valid, base_keys,
                           eos_id, budget, temperature, top_k, top_p,
                           *lane, poison=False):
        """``_verify_slot_impl`` + one prefill lane, same contract as
        ``_decode_block_mixed_impl``."""
        d = self._verify_slot_impl(
            params, cache, tokens, valid, base_keys, eos_id, budget,
            temperature, top_k, top_p, poison=poison)
        ln = self._lane_chunk(params, d[0], *lane)
        return (ln[0],) + d[1:] + ln[1:]

    # ---- host-facing API ---------------------------------------------------

    def shard_params(self, params):
        """Place a (global) parameter pytree onto this engine's mesh with
        the model's training shardings — TP column/row splits land on their
        devices, no resharding at step time."""
        return jax.tree.map(jax.device_put, params,
                            named_shardings(self.topo, self._pspecs))

    # ---- multi-tenant adapters (inference/tenancy.py) ----------------------

    def _adapter_leaves(self) -> dict:
        """The pack's device arrays, placed with the engine's adapter
        shardings (cached inside the pack by version, so hot add/remove
        re-places at the next dispatch and steady state pays nothing)."""
        return self.adapters.device_leaves(
            lambda name, side, arr: jax.device_put(
                arr, self._adapter_sh[name][side]))

    def bind_adapter_ids(self, params, adapter_ids, n: int):
        """Wrap ``params`` with the adapter pack + this dispatch's
        per-row adapter slot ids (``adapter_ids`` — [n] ints, or None
        for all-null). The segmented matmul gathers each row's pair, so
        one dispatch mixes tenants; slot 0 rows bypass exactly. On an
        engine without an adapter pack this is the identity (and passing
        ids is an error — the caller thinks tenants exist)."""
        if self.adapters is None:
            if adapter_ids is not None:
                raise ValueError(
                    "engine has no adapter pack (construct with "
                    "adapters=tenancy.AdapterPack) but adapter ids were "
                    "passed")
            return params
        if adapter_ids is None:
            ids = np.zeros(n, np.int32)
        else:
            ids = np.asarray(adapter_ids, np.int32).reshape(-1)
            if ids.shape[0] != n:
                raise ValueError(
                    f"adapter_ids has {ids.shape[0]} rows; this dispatch "
                    f"carries {n}")
            if (ids < 0).any() or (ids >= self.adapters.slots).any():
                raise ValueError(
                    f"adapter slot ids must be in [0, "
                    f"{self.adapters.slots}); got {ids.tolist()}")
        return llama.bind_adapters(params, self._adapter_leaves(),
                                   jnp.asarray(ids))

    def init_cache(self) -> dict:
        """Fresh zeroed cache, sharded on the engine mesh. For the paged
        layout this also resets the host allocator (pool, radix cache,
        block tables) — a new cache means every parked byte is gone, so
        the batcher's cache-lost rebuild gets a coherent empty pool."""
        if self.paged is not None:
            self.paged.reset()
        return self._init_cache_jit()

    def make_draft_program(self, with_head: bool = False):
        """Build the learned drafter's jitted dispatch (EAGLE-style —
        Li et al. 2024: draft from the target's own last hidden state,
        reusing its embedding and lm_head; Medusa-style cheap heads are
        the degenerate no-trunk case). One small program proposes
        ``spec_len`` greedy continuation tokens for EVERY slot:

            (params[, head], hidden [B, H], tokens [B]) -> drafts [B, G]

        Each step folds the current token's embedding into the running
        pseudo-hidden state (``hidden + embed(tok)`` — the residual-merge
        default that needs NO extra parameters, or ``tanh(concat(embed,
        hidden) @ head['w'])`` when tiny-head params are supplied, e.g.
        via ``checkpoint.load_params``), reads the shared LM head over it
        (final norm included — the exact logits path the target uses) and
        takes the argmax. Deterministic by construction, so the proposal
        is the point-mass distribution ``sampling.speculative_accept``
        assumes. No KV is read or written: the whole draft costs
        ``spec_len`` embedding rows + head matmuls — the "small jitted
        dispatch" next to a verify's full model pass."""
        if self.spec_len < 1:
            raise ValueError(
                "make_draft_program needs a speculative engine "
                "(spec_len > 0)")
        G = self.spec_len
        cfg = self.cfg

        def impl(params, *args):
            if with_head:
                head, hidden, tok = args
            else:
                hidden, tok = args
                head = None

            def step(carry, _):
                h, t = carry
                e = llama.embed_lookup(
                    params["embed"], t[:, None])[:, 0].astype(h.dtype)
                if head is not None:
                    x = jnp.tanh(jnp.concatenate([e, h], axis=-1)
                                 @ head["w"].astype(h.dtype))
                else:
                    x = h + e
                logits = tp_gather(
                    llama.head_logits(params, x[:, None, :], cfg))[:, 0]
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (x, nxt), nxt

            (_, _), out = lax.scan(step, (hidden, tok), None, length=G)
            return jnp.swapaxes(out, 0, 1)  # [B, G]

        head_spec = ({"w": P()},) if with_head else ()
        # base pspecs, NOT the adapter-wrapped dispatch specs: the draft
        # reads only embed/final_norm/lm_head, and its caller (the
        # LearnedDrafter) holds the UNBOUND base tree — adapters shape
        # per-token logits through verify, never through the draft.
        # Per-slot rows shard over dp like every batch family (the draft
        # is embarrassingly parallel over slots — no cache, no cross-row
        # reads).
        dpP = P("dp") if self.dp_size > 1 else P()
        return jax.jit(shard_map(
            impl, self.topo.mesh,
            in_specs=(self._pspecs,) + head_spec + (dpP, dpP),
            out_specs=dpP))

    # ---- paged-layout host plumbing ---------------------------------------

    def _sync_tables(self, cache) -> dict:
        """Ship the host allocator's block-table master to the device
        (replacing the donated copy the last dispatch consumed). Tiny
        ([slots, max_pages] int32) and unconditional — simpler than dirty
        tracking and invisible next to a model dispatch. hot_bf16 policy
        engines refresh the per-page read flags from live refcounts in
        the same breath, so sharing changes take effect next dispatch."""
        out = {**cache, "block_tables": jnp.asarray(self.paged.tables)}
        if self.page_policy:
            out["page_quant"] = jnp.asarray(self.paged.quant_flags())
        return out

    def _ensure(self, cache, slot: int, from_pos: int, to_pos: int) -> dict:
        """Make rows [from_pos, to_pos) of ``slot`` writable before a
        dispatch: the allocator allocates growth pages and plans
        copy-on-writes; the (src, dst) pairs run here as byte-exact
        device page copies. After this, no write the dispatch performs
        can touch a page anyone else holds."""
        for src, dst in self.paged.ensure_writable(slot, from_pos, to_pos):
            cache = self._copy_page_jit(cache, src, dst)
        return cache

    def _pre_write(self, cache, nwrite: int, budget=None,
                   lead=None) -> dict:
        """Before a decode/verify dispatch: every PARKED slot (length > 0)
        writes up to ``nwrite`` rows from its current length — including
        inactive slots' recomputed ghost rows, which the mask hides but
        which must still never land in a shared page. Ensure + COW them
        all, then sync the tables. ``budget`` (decode blocks) caps each
        slot's reach at ``budget[s] + 1`` rows — the emitted run plus the
        one ghost row a stopped slot keeps rewriting — so page demand
        tracks what the dispatch can actually produce, which is what the
        batcher's admission pricing reserves.

        ``lead`` [slots] (overlap pipeline, defer_advance) is the extra
        reach the IN-FLIGHT round may still add to each slot:
        ``host_len`` is one round stale at issue time, so the true device
        length sits anywhere in [host_len, host_len + lead[s]] — the
        ensure window stretches by lead[s] to cover every row the stacked
        rounds can touch. Re-ensuring rows the previous round already
        owns is a no-op (exclusive pages stay exclusive), so the stretch
        costs nothing in steady state."""
        p = self.paged
        window = p.max_pages * p.page_len
        if budget is not None:
            budget = np.asarray(budget)
        if lead is not None:
            lead = np.asarray(lead)
        for s in np.flatnonzero(p.host_len > 0):
            n = nwrite if budget is None else min(
                nwrite, int(budget[s]) + 1)
            if lead is not None:
                n += int(lead[s])
            cache = self._ensure(cache, int(s), int(p.host_len[s]),
                                 min(int(p.host_len[s]) + n, window))
        return self._sync_tables(cache)

    def apply_advance(self, counts) -> None:
        """Deferred paged length advance (overlap pipeline): when
        ``defer_advance`` is set, decode_block/verify skip their host_len
        bookkeeping at issue time — the per-slot counts are still futures
        — and the batcher's sync stage calls this with the materialized
        (and late-finish-masked) counts instead. No-op on contiguous
        engines, whose device-side length pointers are the only length
        state."""
        if self.paged is not None:
            self.paged.advance(np.asarray(counts, np.int64))

    def prefill_bucket(self, prompt_len: int) -> int:
        """Power-of-two padding bucket for a prompt (one compile each)."""
        if prompt_len > self.max_seq_len:
            raise ValueError(
                f"prompt of {prompt_len} tokens exceeds max_seq_len "
                f"{self.max_seq_len}")
        b = self.min_prefill_bucket
        while b < prompt_len:
            b *= 2
        return min(b, self.max_seq_len)

    def _sample_args(self, sample) -> tuple:
        """Normalize a host caller's ``sample=(key, temperature, top_k,
        top_p)`` into the epilogue's device operands — and enforce that
        callers and the engine agree on WHERE sampling happens, so a
        host-sampling caller can never silently read a token id as
        logits (or vice versa)."""
        if not self.sample_on_device:
            if sample is not None:
                raise ValueError(
                    "this engine samples host-side (inference."
                    "sample_on_device: false); drop the sample argument "
                    "or build the engine with sample_on_device=True")
            return ()
        if sample is None:
            raise ValueError(
                "this engine runs the on-device sampling epilogue "
                "(inference.sample_on_device: true); pass sample=(key, "
                "temperature, top_k, top_p) so the dispatch can draw the "
                "next token without shipping logits to the host")
        key, temperature, top_k, top_p = sample
        return (jnp.asarray(key),
                jnp.asarray(np.asarray(temperature, np.float32).reshape(1)),
                jnp.asarray(np.asarray(top_k, np.int32).reshape(1)),
                jnp.asarray(np.asarray(top_p, np.float32).reshape(1)))

    def _lane_args(self, lanes) -> tuple:
        """Build the mixed programs' lane operand tail from per-shard
        lane feeds. ``lanes`` is None (every lane idle) or a list of
        ``dp_size`` entries, each None or a dict with ``slot`` (GLOBAL
        slot id on that shard), ``tokens`` (the chunk's 1..prefill_chunk
        real token ids), ``start`` (first write row — the caller applies
        the contiguous window slide / paged absolute convention,
        ``prefill_chunked``'s exact rule), and on a sample_on_device
        engine ``key``/``temperature``/``top_k``/``top_p`` (the SAME
        fold-at-len(prompt)-1 key every chunk of the serial path
        samples with), plus ``adapter`` on a tenancy engine. Idle lanes
        pad to fixed shapes (valid = 0) so the compiled program never
        changes."""
        dp = self.dp_size
        C = self.prefill_chunk
        toks = np.zeros((dp, C), np.int32)
        slot = np.zeros(dp, np.int32)
        start = np.zeros(dp, np.int32)
        valid = np.zeros(dp, np.int32)
        keyrows = np.zeros((dp, 2), np.uint32)
        temp = np.ones(dp, np.float32)
        topk = np.zeros(dp, np.int32)
        topp = np.ones(dp, np.float32)
        adapter = np.zeros(dp, np.int32)
        if lanes is not None:
            if len(lanes) != dp:
                raise ValueError(
                    f"lanes carries {len(lanes)} entries; this engine "
                    f"serves one lane per dp shard ({dp})")
            for sh, ln in enumerate(lanes):
                if ln is None:
                    continue
                g = int(ln["slot"])
                lo = sh * self.slots_per_shard
                if not lo <= g < lo + self.slots_per_shard:
                    raise ValueError(
                        f"lane slot {g} does not live on dp shard {sh} "
                        f"(slots [{lo}, {lo + self.slots_per_shard}))")
                chunk = np.asarray(ln["tokens"], np.int32).reshape(-1)
                if not 0 < chunk.size <= C:
                    raise ValueError(
                        f"lane chunk must carry 1..prefill_chunk ({C}) "
                        f"real tokens; got {chunk.size}")
                slot[sh] = g - lo
                start[sh] = int(ln["start"])
                toks[sh, : chunk.size] = chunk
                valid[sh] = chunk.size
                if self.sample_on_device:
                    keyrows[sh] = np.asarray(ln["key"]).reshape(2)
                    temp[sh] = np.float32(ln.get("temperature", 1.0))
                    topk[sh] = np.int32(ln.get("top_k", 0))
                    topp[sh] = np.float32(ln.get("top_p", 1.0))
                if self.adapters is not None:
                    adapter[sh] = int(ln.get("adapter") or 0)
        args = (jnp.asarray(toks), jnp.asarray(slot), jnp.asarray(start),
                jnp.asarray(valid))
        if self.sample_on_device:
            args += (jnp.asarray(keyrows), jnp.asarray(temp),
                     jnp.asarray(topk), jnp.asarray(topp))
        if self.adapters is not None:
            args += (jnp.asarray(adapter),)
        return args

    def _lane_ensure(self, cache, lanes) -> dict:
        """Paged pre-write for the lane chunks: make every active lane's
        real rows [start, start + len(tokens)) writable (growth alloc +
        COW) BEFORE the fused dispatch — the caller's ``_pre_write``
        follows and ships the synced tables. Trailing pad rows target
        unallocated table entries and drop to the NULL page, exactly
        like the serial chunk dispatch."""
        if self.paged is None or lanes is None:
            return cache
        for ln in lanes:
            if ln is None:
                continue
            s0 = int(ln["start"])
            n = int(np.asarray(ln["tokens"]).reshape(-1).size)
            cache = self._ensure(cache, int(ln["slot"]), s0, s0 + n)
        return cache

    def prefill(self, params, prompt_ids, sample=None,
                adapter_id=None) -> tuple:
        """Run one prompt through the full-sequence model. Returns
        (kv_blocks, last_logits [1, V] fp32) — or, on a
        ``sample_on_device`` engine (which REQUIRES ``sample=(key,
        temperature, top_k, top_p)``), (kv_blocks, sampled token [1]
        int32): the fused epilogue draws the first generated token inside
        the dispatch and the full-vocab logits never cross to the host.
        A ``return_hidden`` engine appends the prompt's last-token
        pre-final-norm hidden state [1, H]. Pads to the prompt's bucket
        host-side; jit reuses one executable per bucket size."""
        samp = self._sample_args(sample)
        if self.adapters is not None or adapter_id is not None:
            params = self.bind_adapter_ids(
                params, None if adapter_id is None else [adapter_id], 1)
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError("empty prompt")
        bucket = self.prefill_bucket(ids.size)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, : ids.size] = ids
        self._hook("prefill")
        # resolved inside the lambda like every hot-path program, so the
        # flash->dense fallback's rebuilt jit is what a re-dispatch runs
        return self._dispatch(lambda: self._prefill_jit(
            params, jnp.asarray(padded),
            jnp.asarray([ids.size], jnp.int32), *samp))

    def prefill_chunked(self, params, cache, prompt_ids, slot: int,
                        start: int = 0, sample=None,
                        adapter_id=None) -> tuple:
        """Prefill one prompt as fixed-width chunk dispatches writing K/V
        straight into ``slot`` (consumes ``cache``). Returns (cache,
        last_logits [1, V] fp32) — or (cache, sampled token [1] int32) on
        a ``sample_on_device`` engine: every chunk runs the epilogue from
        the SAME key (only the final chunk's draw is consumed, so the key
        chain matches the host sampler's exactly) and no chunk ever ships
        logits. One compiled shape regardless of prompt length; the
        ragged final chunk pads to the chunk width with rows past the
        final length unreachable.

        ``start`` > 0 resumes past an already-parked prefix (the paged
        prefix-sharing admission: rows [0, start) are cached pages the
        chunks attend over but never recompute). ``prompt_ids`` is always
        the FULL prompt — chunk positions are absolute."""
        samp = self._sample_args(sample)
        if self.adapters is not None or adapter_id is not None:
            params = self.bind_adapter_ids(
                params, None if adapter_id is None else [adapter_id], 1)
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        if ids.size == 0:
            raise ValueError("empty prompt")
        if ids.size > self.max_seq_len:
            raise ValueError(
                f"prompt of {ids.size} tokens exceeds max_seq_len "
                f"{self.max_seq_len}")
        if not 0 <= start < ids.size:
            raise ValueError(
                f"chunked-prefill start {start} outside prompt of "
                f"{ids.size} tokens")
        C = self.prefill_chunk
        logits = None
        hidden = None
        for s0 in range(start, ids.size, C):
            end = min(s0 + C, ids.size)
            if self.paged is None:
                # the write window is the chunk's full [w0, w0 + C) rows;
                # past max_seq_len, dynamic_update_slice would CLAMP the
                # start and silently shift the chunk onto earlier rows —
                # instead slide the window back and re-feed the overlap
                # tokens, whose rows recompute to the values already
                # parked there (same prefix, same positions, same program)
                w0 = min(s0, self.max_seq_len - C)
            else:
                # the paged scatter has no clamp hazard (rows past the
                # window drop to the NULL page), so the chunk never
                # slides — critical for the prefix-sharing resume, where
                # a slid window would re-feed (and pointlessly COW) the
                # shared prefix it exists to skip
                w0 = s0
            chunk = ids[w0:end]
            padded = np.zeros((1, C), np.int32)
            padded[0, : chunk.size] = chunk
            if self.paged is not None:
                # COW/alloc every page holding REAL chunk rows ([w0, end)
                # — the trailing pad rows target unallocated entries and
                # drop to the NULL page)
                cache = self._ensure(cache, slot, w0, end)
                cache = self._sync_tables(cache)
            self._hook("prefill_chunk")
            out = self._dispatch(lambda: self._prefill_chunk_jit(
                params, cache, jnp.asarray(padded),
                jnp.asarray(slot, jnp.int32),
                jnp.asarray(w0, jnp.int32),
                jnp.asarray(chunk.size, jnp.int32), *samp))
            if self.return_hidden:
                cache, logits, hidden = out
            else:
                cache, logits = out
            if self.paged is not None:
                self.paged.set_len(slot, end)
        if self.return_hidden:
            # the FINAL chunk's last-token hidden state is the prompt's
            return cache, logits, hidden
        return cache, logits

    def prefill_paged(self, params, cache, prompt_ids, slot: int,
                      sample=None, adapter_id=None,
                      cache_salt: str = "") -> tuple:
        """Paged admission: prefix-match, share, and prefill one prompt
        into ``slot`` (consumes ``cache``). Returns (cache, last_logits
        [1, V] fp32 — or the sampled token [1] int32 on a
        ``sample_on_device`` engine — n_dispatches, cached_tokens).

        The radix cache resolves the longest cached prefix; its pages are
        shared into the slot (refcount bumps — ZERO prefill work for
        those tokens) and only the suffix runs through the model, as
        chunk dispatches attending over the shared pages. A miss takes
        exactly the contiguous path's dispatches (pow-2-bucketed one-shot
        at or under ``prefill_chunk``, chunked above it) so paged-vs-
        contiguous generations stay bit-identical. Either way the
        prompt's pages are then registered in the radix cache for the
        next request — the first decode write past the prompt COWs the
        tail page rather than mutate what the cache now holds."""
        if self.paged is None:
            raise ValueError("prefill_paged needs kv_layout='paged'")
        ids = [int(t) for t in np.asarray(prompt_ids, np.int32).reshape(-1)]
        if not ids:
            raise ValueError("empty prompt")
        rh = self.return_hidden
        hidden = None
        cached = self.paged.match_prefix(slot, ids, salt=cache_salt)
        if cached > 0:
            cache = self._set_length_jit(self._sync_tables(cache), slot,
                                         cached)
            out = self.prefill_chunked(params, cache, ids, slot,
                                       start=cached, sample=sample,
                                       adapter_id=adapter_id)
            cache, logits = out[:2]
            hidden = out[2] if rh else None
            n = -(-(len(ids) - cached) // self.prefill_chunk)
        elif len(ids) <= self.prefill_chunk:
            out = self.prefill(params, ids, sample=sample,
                               adapter_id=adapter_id)
            kv, logits = out[:2]
            hidden = out[2] if rh else None
            cache = self.insert(cache, kv, slot, len(ids))
            n = 1
        else:
            out = self.prefill_chunked(params, cache, ids, slot,
                                       sample=sample, adapter_id=adapter_id)
            cache, logits = out[:2]
            hidden = out[2] if rh else None
            n = -(-len(ids) // self.prefill_chunk)
        self.paged.register_prompt(slot, ids, salt=cache_salt)
        base = (cache, logits, n, cached)
        return base + (hidden,) if rh else base

    # ---- page transport (prefill/decode disaggregation) -------------------

    def transport_spec(self) -> dict:
        """The engine's page-layout fingerprint for the KV-page transport
        (inference/page_transport.py) — what a peer must match to
        exchange page bytes with this replica."""
        from picotron_tpu.inference import page_transport

        return page_transport.transport_spec(self)

    def export_prefix(self, cache, ids, first_token=None,
                      cache_salt: str = "") -> dict:
        """Serialize the longest radix-cached prefix of ``ids`` as a
        transport payload (paged engines only): pinned pages, byte-exact
        leaves, CRC. ``first_token`` rides along when the match covers
        the whole prompt — the disaggregated handoff's seat state.
        ``cache_salt`` (the tenant) keys the lookup AND rides the
        payload, so a handoff can only land in the same tenant's
        subtree on the receiver."""
        from picotron_tpu.inference import page_transport

        return page_transport.export_prefix(self, cache, ids,
                                            first_token=first_token,
                                            tenant=cache_salt)

    def import_prefix(self, cache, payload) -> tuple:
        """Land a transport payload's pages in the local pool + radix
        cache (consumes ``cache``; returns (cache, info)). Only locally
        missing chunks allocate; failures release every allocated page
        before propagating (refcount-correct under the dispatch retry)."""
        from picotron_tpu.inference import page_transport

        return page_transport.import_prefix(self, cache, payload)

    def seat_slot(self, cache, slot: int, length: int) -> dict:
        """Park an imported, fully cached prefix as ``slot``'s
        ready-to-decode state (consumes ``cache``): device length pointer
        + synced tables, NO dispatch. The caller already shared the pages
        into the slot (``paged.match_prefix(..., cap_last=False)``)."""
        if self.paged is None:
            raise ValueError("seat_slot needs kv_layout='paged'")
        self.paged.set_len(slot, length)
        return self._set_length_jit(self._sync_tables(cache), slot, length)

    def _page_bytes(self) -> int:
        """Raw bytes one pool page holds across every storage leaf (the
        migration accounting unit — same figure the transport's
        ``bytes_total`` reports per page)."""
        spec = self.transport_spec()
        return sum(np.dtype(l["dtype"]).itemsize * int(np.prod(l["shape"]))
                   for l in spec["leaves"].values())

    def migrate_slot(self, cache, src: int, dst: int, prompt_ids=None,
                     cache_salt: str = "") -> tuple:
        """Move a parked slot's KV pages from global slot ``src`` into
        (empty) global slot ``dst`` through the page-transport device
        path — ONE batched gather + ONE donating write, byte-exact —
        then re-seat the slot's host/device state (consumes ``cache``).
        The dp rebalance planner's primitive: with ``dst`` on a
        different dp shard the pages land in THAT shard's pool strip, so
        a skewed shard sheds a whole parked slot. Works under dp == 1
        too (a plain slot move within one pool).

        All-or-nothing: destination-pool exhaustion
        (``PagePoolExhausted`` from the all-or-nothing allocation) or
        any fault before the donating write completes releases every
        destination page and leaves the source slot untouched —
        refcounts conserved either way. ``host_len`` already reflects
        only ACCEPTED tokens (a verify's advance ran before anyone could
        park the slot), so draft rows a speculative round wrote past the
        length pointer are rolled back by construction — never exported.

        ``prompt_ids`` (+ ``cache_salt`` = tenant) re-grafts the slot's
        prompt into the destination shard's radix domain, so prefix
        sharing survives the move. Returns (cache, bytes_moved)."""
        if self.paged is None:
            raise ValueError("migrate_slot needs kv_layout='paged'")
        p = self.paged
        if not (0 <= src < self.slots and 0 <= dst < self.slots):
            raise ValueError(
                f"migrate_slot: slots out of range: {src} -> {dst} "
                f"(engine has {self.slots})")
        if src == dst:
            return cache, 0
        n_tok = int(p.host_len[src])
        if n_tok <= 0:
            raise ValueError(f"migrate_slot: source slot {src} is empty")
        if int(p.host_len[dst]) > 0:
            raise ValueError(
                f"migrate_slot: destination slot {dst} is occupied")
        npages = p.pages_for(n_tok)
        src_pids = np.asarray(p.tables)[src, :npages].astype(np.int32)
        # all-or-nothing allocation on the DESTINATION slot's shard:
        # exhaustion raises here, before anything moved
        if self.dp_size > 1:
            dsh = p.shards[p.shard_of(dst)]
            base = p.shard_of(dst) * p.pages_per_shard
            new_pids = [base + q for q in dsh.alloc_import(npages)]
        else:
            dsh, base = p, 0
            new_pids = p.alloc_import(npages)
        bucket = 1
        while bucket < npages:
            bucket *= 2
        src_arr = np.full(bucket, paged_kv.NULL_PAGE, np.int32)
        src_arr[:npages] = src_pids
        dst_arr = np.full(bucket, paged_kv.NULL_PAGE, np.int32)
        dst_arr[:npages] = new_pids
        try:
            pages = self._gather_pages_jit(cache, src_arr)
            # a dead dp peer discovered here exits 77 BEFORE the donating
            # write; the except arm keeps restart leak-free regardless
            self._check_monitor()
            cache = self._write_pages_jit(cache, pages, dst_arr)
        except BaseException:
            # the fault struck before the donating dispatch consumed the
            # cache: the fresh pages' only holder is this migration —
            # release them and both pools are exactly as before
            p.release_pages(new_pids)
            raise
        # seat the destination: its table row holds the fresh pages
        # (refcount 1, already owed to the slot), master length/pricing
        # move over, then the source's references drop — shared source
        # pages live on under their other holders
        if self.dp_size > 1:
            dsh.tables[p.local_slot(dst), :npages] = \
                [q - base for q in new_pids]
        else:
            p.tables[dst, :npages] = new_pids
        p.priced[dst] = p.priced[src]
        p.set_len(dst, n_tok)
        p.free_slot(src)
        if prompt_ids is not None:
            p.register_prompt(dst, [int(t) for t in prompt_ids],
                              salt=cache_salt)
        cache = self._set_length_jit(self._sync_tables(cache), dst, n_tok)
        cache = self._release_jit(cache, src)
        return cache, npages * self._page_bytes()

    def insert(self, cache, kv, slot: int, length: int) -> dict:
        """Park a prefill's blocks into ``slot`` (consumes ``cache``).
        On the paged layout this first allocates the slot's pages host-
        side, then scatters the blocks through its block-table row."""
        if self.paged is not None:
            cache = self._ensure(cache, slot, 0, length)
            cache = self._sync_tables(cache)
            self.paged.set_len(slot, length)
        return self._insert_jit(cache, kv, slot, length)

    def release(self, cache, slot: int) -> dict:
        """Free a slot for the next request (consumes ``cache``). Paged:
        drop the slot's page references — exclusively-held pages return
        to the pool, pages shared with the radix cache (or other slots)
        live on for the next prefix hit."""
        if self.paged is not None:
            self.paged.free_slot(slot)
            cache = self._sync_tables(cache)
        return self._release_jit(cache, slot)

    def decode_step(self, params, cache, tokens, key, temperature,
                    top_k, top_p, adapter_ids=None) -> tuple:
        """One token for every slot. tokens/temperature/top_k/top_p are
        [slots] host or device arrays; returns (cache, next_tokens [slots],
        logits [slots, V] fp32). On a ``sample_on_device`` engine the
        logits slot is None — the [B, V] array never leaves the device
        (the [B] token ids are the dispatch's whole host payload). A
        ``return_hidden`` engine appends hidden [slots, H] (the step's
        pre-final-norm hidden states — the learned drafter's input).
        Consumes ``cache``."""
        if self.key_schedule == "slot":
            raise ValueError(
                "decode_step is round-keyed (one shared key per step) and "
                "a key_schedule='slot' engine samples with per-slot "
                "position-folded keys — use decode_block, whose slot "
                "variant owns the schedule")
        self._hook("decode")
        if self.adapters is not None or adapter_ids is not None:
            params = self.bind_adapter_ids(params, adapter_ids, self.slots)
        if self.paged is not None:
            cache = self._pre_write(cache, 1)
        out = self._dispatch(lambda: self._decode_jit(
            params, cache,
            jnp.asarray(np.asarray(tokens, np.int32)), key,
            jnp.asarray(np.asarray(temperature, np.float32)),
            jnp.asarray(np.asarray(top_k, np.int32)),
            jnp.asarray(np.asarray(top_p, np.float32))))
        if self.paged is not None:
            # mirror the device rule: parked slots advanced by one
            self.paged.advance((self.paged.host_len > 0).astype(np.int64))
        if self.sample_on_device:
            if self.return_hidden:
                cache, toks, hid = out
                return cache, toks, None, hid
            cache, toks = out
            return cache, toks, None
        return out

    def decode_block(self, params, cache, tokens, keys, eos_id, budget,
                     temperature, top_k, top_p, adapter_ids=None,
                     lead=None, lanes=None) -> tuple:
        """``decode_block_len`` tokens for every slot in one dispatch.
        ``keys`` is [decode_block_len, 2] (one PRNG key per in-block step)
        on a round-keyed engine, or the per-slot BASE keys [slots, 2] on a
        ``key_schedule='slot'`` engine (positions fold in-trace);
        ``eos_id`` [slots] int32 (−1 = none), ``budget`` [slots] int32
        remaining tokens (0 for free slots). ``tokens`` may be a device
        array — it stays lazy (the overlap pipeline feeds the previous
        round's on-device next-token output straight back in). Returns
        (cache, tokens [slots, decode_block_len], produced counts
        [slots]); a slot-keyed engine appends next_tok [slots] (each
        slot's post-block last token, on device) and a ``return_hidden``
        engine appends hidden [slots, H] — each slot's hidden state at
        its last active step. Consumes ``cache``. ``lead`` forwards to
        ``_pre_write`` (overlap's stale-host_len reach allowance); with
        ``defer_advance`` set the paged length bookkeeping is skipped
        here — the caller's sync stage applies it (``apply_advance``).

        ``lanes`` (mixed_dispatch engines only — see ``_lane_args``)
        feeds each dp shard's fused prefill lane; a mixed engine ALWAYS
        runs the fused program (idle padded lanes when None), so the
        compiled shape never changes. The lane outputs ride at the end
        of the returned tuple: the lane token [dp] (sample_on_device) or
        logits [dp, V], then lane hidden [dp, H] on a return_hidden
        engine."""
        if lanes is not None and not self.mixed:
            raise ValueError(
                "lanes requires a mixed_dispatch engine (construct with "
                "mixed_dispatch=True or set inference.mixed_dispatch)")
        keys = jnp.asarray(keys)
        if self.key_schedule == "slot":
            if keys.shape != (self.slots, 2):
                raise ValueError(
                    f"key_schedule='slot' takes per-slot base keys "
                    f"[slots, 2] = [{self.slots}, 2]; got "
                    f"{tuple(keys.shape)}")
        elif keys.shape[0] != self.decode_block_len:
            raise ValueError(
                f"keys has {keys.shape[0]} rows; decode_block_len is "
                f"{self.decode_block_len} (one key per in-block step)")
        self._hook("decode", budget)
        if self.adapters is not None or adapter_ids is not None:
            params = self.bind_adapter_ids(params, adapter_ids, self.slots)
        poison = self._poison("decode")
        if self.paged is not None:
            cache = self._lane_ensure(cache, lanes)
            cache = self._pre_write(cache, self.decode_block_len,
                                    budget=budget, lead=lead)
        lane_args = self._lane_args(lanes) if self.mixed else ()
        # a device tokens array must NOT round-trip through np.asarray —
        # that sync is exactly what the overlap pipeline exists to avoid
        tok_in = (tokens if isinstance(tokens, jax.Array)
                  else jnp.asarray(np.asarray(tokens, np.int32)))
        # the program is resolved INSIDE the lambda so the flash->dense
        # fallback's rebuilt jits are what a re-dispatch runs
        out = self._dispatch(lambda: self._decode_block_prog(poison)(
            params, cache, tok_in, keys,
            jnp.asarray(np.asarray(eos_id, np.int32)),
            jnp.asarray(np.asarray(budget, np.int32)),
            jnp.asarray(np.asarray(temperature, np.float32)),
            jnp.asarray(np.asarray(top_k, np.int32)),
            jnp.asarray(np.asarray(top_p, np.float32)), *lane_args))
        if self.paged is not None and not self.defer_advance:
            # mirror device length advancement (counts per slot). The
            # host sync this forces is the block's ONE sync, just moved
            # ahead of the batcher's own np.asarray on the same buffers.
            self.paged.advance(np.asarray(out[2], np.int64))
        return out

    def verify(self, params, cache, tokens, key, eos_id, budget,
               temperature, top_k, top_p, draft_len=None,
               adapter_ids=None, lead=None, lanes=None) -> tuple:
        """One speculative draft-verify dispatch for every slot
        (``spec_len > 0`` engines only). ``tokens`` is
        [slots, spec_len + 1] int32 — column 0 is each slot's current last
        token, columns 1..spec_len its drafted continuation; the remaining
        arguments are [slots] arrays exactly as ``decode_block`` takes
        them. ``draft_len`` [slots] int32 (optional) makes the dispatch
        RAGGED: slot b proposed only ``draft_len[b] <= spec_len`` real
        drafts (the controller's per-slot choice) — pad columns past it
        are masked out of acceptance and the K/V write while the compiled
        shape stays [slots, spec_len + 1], so mixed per-slot lengths cost
        no recompile. None = every slot drafted the full spec_len.
        Returns (cache, emitted [slots, spec_len + 1], counts
        [slots], accepted-draft counts [slots]) — ``counts[b]`` leading
        entries of emitted row b are the tokens slot b produced this
        dispatch (1..spec_len + 1 per active slot); a slot-keyed engine
        (``key_schedule='slot'``, where ``key`` is the per-slot base keys
        [slots, 2] and ``tokens`` may be a device array) appends next_tok
        [slots] — each row's on-device last emitted token — and a
        ``return_hidden`` engine appends hidden [slots, H]. Consumes
        ``cache``. ``lead``/``defer_advance``/``lanes``: see
        ``decode_block``."""
        if lanes is not None and not self.mixed:
            raise ValueError(
                "lanes requires a mixed_dispatch engine (construct with "
                "mixed_dispatch=True or set inference.mixed_dispatch)")
        if (self._verify_jit is None and self._verify_slot_jit is None
                and self._verify_mixed_jit is None):
            raise ValueError(
                "speculative decoding is off for this engine (spec_len == "
                "0); construct it with spec_len > 0 or set "
                "inference.spec_len")
        # device tokens stay lazy (overlap feeds column 0 straight from
        # the previous round's on-device next-token output)
        if not isinstance(tokens, jax.Array):
            tokens = np.asarray(tokens, np.int32)
        if tuple(tokens.shape) != (self.slots, self.spec_len + 1):
            raise ValueError(
                f"verify tokens must be [slots, spec_len + 1] = "
                f"[{self.slots}, {self.spec_len + 1}]; got "
                f"{tuple(tokens.shape)}")
        if draft_len is None:
            valid = np.full(self.slots, self.spec_len + 1, np.int32)
        else:
            draft_len = np.asarray(draft_len, np.int32)
            if draft_len.shape != (self.slots,):
                raise ValueError(
                    f"draft_len must be [slots] = [{self.slots}]; got "
                    f"{draft_len.shape}")
            if np.any(draft_len < 0) or np.any(draft_len > self.spec_len):
                raise ValueError(
                    f"draft_len entries must be in [0, spec_len = "
                    f"{self.spec_len}]; got {draft_len.tolist()}")
            valid = draft_len + 1
        if self.key_schedule == "slot":
            # per-slot base keys [slots, 2]; positions fold in-trace
            key = jnp.asarray(key)
            if key.shape != (self.slots, 2):
                raise ValueError(
                    f"key_schedule='slot' takes per-slot base keys "
                    f"[slots, 2] = [{self.slots}, 2]; got "
                    f"{tuple(key.shape)}")
        self._hook("verify", budget)
        if self.adapters is not None or adapter_ids is not None:
            params = self.bind_adapter_ids(params, adapter_ids, self.slots)
        poison = self._poison("verify")
        if self.paged is not None:
            # the verify writes spec_len + 1 rows OPTIMISTICALLY for every
            # parked slot; ensuring them all exclusive BEFORE the dispatch
            # is what makes the rollback free — rejected rows strand in
            # pages only this slot holds, never in a shared one
            cache = self._lane_ensure(cache, lanes)
            cache = self._pre_write(cache, self.spec_len + 1, lead=lead)
        lane_args = self._lane_args(lanes) if self.mixed else ()
        # resolved inside the lambda, exactly like decode_block's program
        out = self._dispatch(lambda: self._verify_prog(poison)(
            params, cache, jnp.asarray(tokens), jnp.asarray(valid), key,
            jnp.asarray(np.asarray(eos_id, np.int32)),
            jnp.asarray(np.asarray(budget, np.int32)),
            jnp.asarray(np.asarray(temperature, np.float32)),
            jnp.asarray(np.asarray(top_k, np.int32)),
            jnp.asarray(np.asarray(top_p, np.float32)), *lane_args))
        if self.paged is not None and not self.defer_advance:
            # device lengths advanced by the ACCEPTED counts (the length
            # pointer is the rollback) — mirror exactly that
            self.paged.advance(np.asarray(out[2], np.int64))
        return out
