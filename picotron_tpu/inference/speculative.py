"""Speculative decoding: drafters + the closed-loop spec_len controller.

Classic speculative decoding (Leviathan et al. 2023, "Fast Inference from
Transformers via Speculative Decoding"; Chen et al. 2023, "Accelerating
Large Language Model Decoding with Speculative Sampling") converts decode
from one model pass per token to one pass per ACCEPTED RUN: a cheap
drafter proposes ``gamma`` tokens, one jitted verify dispatch
(engine.verify — the blocked decode program generalized to gamma+1 query
positions per slot) scores them all, and the distribution-preserving
acceptance rule (sampling.speculative_accept) keeps the matching prefix
plus one fresh token. Every dispatch emits between 1 and gamma+1 tokens,
so dispatches-per-token — the host-sync metric bench_decode.py tracks —
drops below 1 whenever anything accepts, and the output distribution is
untouched (bit-identical for greedy, distributionally identical for
sampled; both test-pinned).

This module holds the DRAFT side plus the policy loop that tunes it:

- ``NgramDrafter`` — prompt-lookup decoding (match the last k tokens
  against the history, propose what followed last time): free, and strong
  exactly where speculation pays — repetitive continuations, code,
  retrieval-grounded generation, and the token loops greedy decoding
  falls into. The suffix index is INCREMENTAL (append-only per slot, keyed
  by the batcher-provided ``ctx``) and the match scan is capped at
  ``window`` recent tokens, so a long-running slot's lookup stays O(1)
  per round instead of re-scanning its whole history.
- ``LearnedDrafter`` — the EAGLE-style learned draft model (Li et al.
  2024): a tiny head over the TARGET's own last hidden state that shares
  the target's embedding and lm_head weights, so no separate draft
  checkpoint exists; optional tiny-head params plug in when available.
  Drafts all slots' gamma tokens in one small jitted dispatch
  (engine.make_draft_program) from the hidden states the engine's
  ``return_hidden`` hook keeps on device.
- ``SpecController`` — the closed policy loop (ROADMAP item 4): reads the
  obs registry's LIVE per-slot draft-proposed/accepted counters and
  per-kind dispatch-latency histograms (the PR 10 instruments, consumed
  here as a CONTROL surface for the first time) and sets ``spec_len``
  per slot each round — ramping up while acceptance x draft cost beats
  plain blocked decode, ramping to 0 (speculation off; the batcher falls
  back to ``decode_block`` once every slot is off) when it does not, and
  switching drafters per slot — with windowed evaluation + consecutive-
  decision hysteresis so adversarial accept-rate flip-flop traffic
  cannot make it oscillate.

Acceptance accounting rides in the batcher (``draft_proposed`` /
``draft_accepted`` / ``accept_rate``): an accept-rate of r means the
average dispatch emitted ~1 + r*gamma tokens. Rates near 0 mean the
drafter is guessing blind (speculation costs nothing but the wider verify
dispatch); rates near 1 mean dispatches-per-token approaches
1/(gamma+1).

Overlapped scheduling staleness contract (``inference.overlap``): under
the zero-bubble pipeline the batcher drafts round N+1 WHILE round N still
executes, so every drafter input — slot histories, ``_last_tok``, the
device hidden rows, the controller's per-slot lens/kinds — is one round
stale. That is safe by construction: the slot-schedule verify program's
sample-and-match acceptance (sampling.speculative_match) makes the
EMITTED stream independent of the draft values, so a stale guess can only
lower the accept rate, never change a token. Controller decisions land at
round boundaries one round late for the same reason (its counters update
at sync). See docs/INFERENCE.md "Overlapped scheduling".
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class Drafter:
    """Proposes draft tokens for one slot from its token history.

    Implementations must be DETERMINISTIC functions of ``history`` — the
    acceptance rule (sampling.speculative_accept) treats the proposal as a
    point-mass distribution, which is what makes rejection resampling
    exact. A stochastic drafter (e.g. a sampled draft model) would need
    its per-token proposal probabilities threaded into the accept rule.

    ``kind`` labels the drafter in telemetry and the controller's
    switching table; ``stateful`` drafters additionally take the
    batcher's per-request ``ctx`` key in ``propose`` and get
    ``begin``/``forget`` lifecycle calls; ``needs_hidden`` drafters
    (the learned family) draft per BATCH from device state instead —
    ``propose_batch`` — and the engine must run with ``return_hidden``.
    """

    kind = "custom"
    stateful = False
    needs_hidden = False

    def propose(self, history: np.ndarray, n: int) -> np.ndarray:
        """Return exactly ``n`` proposed continuation tokens (int32) for a
        slot whose tokens so far (prompt + generated, the yet-unwritten
        last token included) are ``history``. Proposals are speculative by
        definition — a bad guess costs nothing but the rejected verify
        columns — so there is no "no proposal" escape hatch; return a
        best-effort guess."""
        raise NotImplementedError

    def begin(self, ctx) -> None:
        """A request keyed ``ctx`` was admitted (stateful drafters reset
        any per-request index here)."""

    def forget(self, ctx) -> None:
        """The request keyed ``ctx`` finished — drop its state."""


class NgramDrafter(Drafter):
    """Prompt-lookup drafting: match the longest suffix n-gram (``ngram``
    down to 1 tokens) of the history against its earlier occurrences and
    propose the ``n`` tokens that followed the MOST RECENT match. A match
    near the end of the history cycles its continuation (the region from
    the match to the end is exactly the pattern being repeated), which is
    what catches greedy token loops and boilerplate. No match at any
    length falls back to repeating the last token.

    ``window`` > 0 caps the match scan at the most recent ``window``
    history tokens (a match whose continuation starts earlier is
    ignored); 0 scans everything.

    Two lookup paths, pinned equal in tests/test_speculative.py:

    - stateless (``ctx=None``): full suffix scan over the history each
      call — the reference semantics;
    - incremental (``ctx=<request key>``): an append-only per-request
      index maps every k-gram to its most recent indexed end position;
      each call extends the index by the tokens appended since the last
      call and answers with dict lookups — O(new tokens) per round
      instead of O(history). The final gram (the query suffix itself) is
      deliberately indexed one call LATE, which is exactly the "match
      must have a continuation" exclusion of the full scan.
    """

    kind = "ngram"
    stateful = True

    def __init__(self, ngram: int = 3, window: int = 0):
        if ngram < 1:
            raise ValueError("ngram must be >= 1")
        if window < 0:
            raise ValueError("window must be >= 0 (0 = unbounded)")
        self.ngram = int(ngram)
        self.window = int(window)
        self._idx: dict = {}  # ctx -> {"done": int, "maps": [dict] * ngram}

    def begin(self, ctx) -> None:
        self._idx.pop(ctx, None)

    def forget(self, ctx) -> None:
        self._idx.pop(ctx, None)

    def _continuation(self, h: np.ndarray, end: int, n: int) -> np.ndarray:
        """The ``n``-token proposal from a match whose gram ends at
        ``end``: cycle the continuation out to n tokens — after a match
        near the end, the tail IS the expected future of the loop."""
        return np.resize(h[end + 1:], n).astype(np.int32)

    def _min_end(self, L: int) -> int:
        """Earliest gram-end position the window admits as a match."""
        return 0 if self.window <= 0 else max(0, L - 1 - self.window)

    def propose(self, history: np.ndarray, n: int,
                ctx=None) -> np.ndarray:
        h = np.asarray(history, np.int32).reshape(-1)
        if n < 1:
            return np.zeros(0, np.int32)
        if h.size < 2:
            fill = h[-1] if h.size else 0
            return np.full(n, fill, np.int32)
        if ctx is not None:
            return self._propose_indexed(h, n, ctx)
        return self._propose_scan(h, n)

    def _propose_scan(self, h: np.ndarray, n: int) -> np.ndarray:
        """The full-rebuild reference: scan every candidate each call."""
        lo = self._min_end(h.size)
        for k in range(min(self.ngram, h.size - 1), 0, -1):
            suffix = h[-k:]
            # candidate starts i with i + k <= len - 1: the match must have
            # at least one continuation token (the final occurrence — the
            # suffix itself — is excluded by construction)
            windows = np.lib.stride_tricks.sliding_window_view(
                h[: h.size - 1], k)
            hits = np.flatnonzero((windows == suffix).all(axis=1))
            # window cap on the match's END position (hit start + k - 1)
            hits = hits[hits + k - 1 >= lo]
            if hits.size:
                return self._continuation(h, int(hits[-1]) + k - 1, n)
        return np.full(n, h[-1], np.int32)

    def _propose_indexed(self, h: np.ndarray, n: int, ctx) -> np.ndarray:
        """Incremental path: extend the per-request index by the newly
        appended tokens, then answer the suffix lookup from the maps."""
        st = self._idx.get(ctx)
        if st is None or st["done"] > h.size - 1:
            # unknown request, or a history that shrank (slot recycled
            # without begin()) — start a fresh index
            st = {"done": 0, "maps": [dict() for _ in range(self.ngram)]}
            self._idx[ctx] = st
        maps = st["maps"]
        # index gram ENDS e in [done, len-2]: ends at len-1 would be the
        # query suffix itself — no continuation yet, indexed next call.
        # Only the tokens the new grams can touch are materialized, so a
        # round's host cost tracks the APPENDED tokens, not the history
        # (every gram end e >= done reaches back at most ngram - 1).
        base = max(0, st["done"] - self.ngram + 1)
        tail = h[base:].tolist()
        for e in range(st["done"], h.size - 1):
            for k in range(1, min(self.ngram, e + 1) + 1):
                maps[k - 1][tuple(tail[e - k + 1 - base: e + 1 - base])] = e
        st["done"] = h.size - 1
        lo = self._min_end(h.size)
        for k in range(min(self.ngram, h.size - 1), 0, -1):
            e = maps[k - 1].get(tuple(tail[h.size - k - base:]))
            if e is not None and e >= lo:
                return self._continuation(h, e, n)
        return np.full(n, h[-1], np.int32)


class LearnedDrafter(Drafter):
    """EAGLE-style learned drafting from the target's own last hidden
    state. The engine's ``return_hidden`` hook keeps each slot's
    pre-final-norm hidden state (at the position whose logits produced
    the slot's current last token) ON DEVICE; one small jitted dispatch
    (engine.make_draft_program) then autoregresses a pseudo-hidden state
    through the SHARED embedding + lm_head for ``spec_len`` greedy steps
    — no separate draft checkpoint, no KV traffic, no [B, vocab] logits
    crossing to the host (the dispatch ships [B, spec_len] token ids).

    ``head`` (optional) is a tiny-head parameter tree ``{"w": [2H, H]}``
    — load one with ``checkpoint.load_params`` next to the target's
    weights, or pass None for the parameter-free residual merge
    (``hidden + embed(token)``), which needs nothing beyond the target
    checkpoint. Either way the proposal is a deterministic function of
    (hidden, token), so the acceptance rule's point-mass assumption
    holds and greedy output stays bit-identical to spec-off."""

    kind = "learned"
    needs_hidden = True

    def __init__(self, engine, params, head: Optional[dict] = None):
        if engine.spec_len < 1:
            raise ValueError(
                "LearnedDrafter needs a speculative engine (spec_len > 0)")
        if not engine.return_hidden:
            raise ValueError(
                "LearnedDrafter needs the engine's last-hidden-state hook"
                " — build the engine with inference.drafter: 'learned' "
                "(or return_hidden=True)")
        self.engine = engine
        self.params = params
        self.head = head
        self._jit = engine.make_draft_program(with_head=head is not None)

    def propose_batch(self, tokens, hidden, n: int) -> np.ndarray:
        """Draft ``n`` tokens for EVERY slot in one dispatch: ``tokens``
        [B] (each slot's current last token, host or device), ``hidden``
        [B, H] (the engine-returned device hidden states). ``n`` must be
        the engine's ``spec_len`` — the program's compiled length; ragged
        per-slot lengths are the verify mask's job, so callers slice the
        prefix they need. Returns host int32 [B, n].

        The overlap pipeline passes the HOST ``_last_tok`` view here even
        though it is one round stale (passing the device-carried token
        row would host-sync on the in-flight round — the bubble the
        pipeline exists to remove); a stale conditioning token only costs
        acceptance, never correctness (module docstring)."""
        import jax.numpy as jnp

        if n != self.engine.spec_len:
            raise ValueError(
                f"the draft program proposes exactly spec_len = "
                f"{self.engine.spec_len} tokens per slot, got n = {n} "
                f"(slice the per-slot prefix you need)")
        toks = jnp.asarray(np.asarray(tokens, np.int32))
        head = (self.head,) if self.head is not None else ()
        return np.asarray(self._jit(self.params, *head, hidden, toks))

    def propose(self, history, n, ctx=None):
        raise TypeError(
            "LearnedDrafter drafts per batch from device hidden states "
            "(propose_batch); per-slot host proposal is the n-gram "
            "drafter's path")


def init_draft_head(key, hidden_size: int, dtype=np.float32) -> dict:
    """A randomly initialized tiny-head parameter tree for
    ``LearnedDrafter`` (the shape ``checkpoint.load_params`` would
    restore): one [2H, H] merge matrix, U(-1/sqrt(2H), 1/sqrt(2H))."""
    import jax

    bound = 1.0 / np.sqrt(2.0 * hidden_size)
    w = jax.random.uniform(key, (2 * hidden_size, hidden_size),
                           np.float32, -bound, bound)
    return {"w": w.astype(dtype)}


class SpecController:
    """The per-slot speculation policy loop (docs/INFERENCE.md
    "Self-tuning speculation").

    Telemetry as a control surface: the batcher mirrors every round's
    per-slot draft counts into the obs registry
    (``picotron_slot_draft_proposed_total{slot=...}`` / ``..accepted..``)
    and every dispatch's wall time into
    ``picotron_dispatch_seconds{kind}``; the controller reads BOTH live
    and decides, per slot, the next round's draft length and drafter:

    - each slot re-evaluates only after proposing ``window`` draft tokens
      since its last decision (one bad round cannot flip policy);
    - the windowed accept rate r picks a direction: r >= ``target`` ramps
      UP (spec_len doubles toward the engine ceiling), r < ``low`` ramps
      DOWN (halves toward 0); the [low, target) band holds;
    - the measured cost ratio joins once the latency histograms hold
      ``latency_min_samples`` per kind: speculation must also PAY —
      (1 + r*g) tokens per (verify + draft) dispatch must beat the
      blocked-decode alternative's block_len tokens per decode dispatch
      — or the direction is forced down / the ramp-up vetoed;
    - a ramp applies only after ``hysteresis`` CONSECUTIVE evaluations
      agree on the direction (flip-flopping traffic alternates the
      direction, the streak never completes, spec_len holds — pinned in
      tests);
    - ramping down past spec_len 1 first SWITCHES drafters (when the
      batcher registered more than one kind and the other is untried
      since the slot's last reset), then turns speculation OFF (spec_len
      0). An off slot re-probes with a 1-token draft after ``cooloff``
      rounds, so traffic that turns easy is rediscovered;
    - every decision lands in
      ``picotron_spec_controller_decisions_total{action}``.

    When EVERY occupied slot is off the batcher skips the verify dispatch
    entirely and falls back to ``engine.decode_block`` — speculation
    "gets out of the way" instead of paying verify width for nothing.
    """

    def __init__(self, cfg, registry, *, slots: int, max_spec_len: int,
                 block_len: int, kinds=("ngram",)):
        if max_spec_len < 1:
            raise ValueError("SpecController needs max_spec_len >= 1")
        if not kinds:
            raise ValueError("SpecController needs at least one drafter")
        self.cfg = cfg
        self.registry = registry
        self.slots = int(slots)
        self.gmax = int(max_spec_len)
        self.block_len = int(block_len)
        self.kinds = tuple(kinds)
        self._decisions = {}
        self._g = [self.gmax] * self.slots  # optimistic start: full draft
        self._kind = [self.kinds[0]] * self.slots
        self._streak = [0] * self.slots
        self._idle = [0] * self.slots
        self._tried: list = [{self.kinds[0]} for _ in range(self.slots)]
        self._snap = [(0.0, 0.0)] * self.slots  # counter values at last eval
        # per-slot TPOT SLO in SECONDS (None = best-effort): tokens
        # arrive in per-dispatch bursts, so the inter-token gap a client
        # sees is the dispatch wall time — a slot whose measured
        # verify+draft latency exceeds its SLO gets its draft length
        # halved regardless of accept rate (multi-tenant serving's SLO
        # input; the batcher sets it at admission via reset())
        self._slo: list = [None] * self.slots
        # shadow tallies so the loop still closes under obs.enabled:
        # false (the NullRegistry's counters read 0 forever)
        self._local = [(0.0, 0.0)] * self.slots

    # ---- registry reads (the control surface) -----------------------------

    def record(self, slot: int, proposed: int, accepted: int) -> None:
        """Mirror one round's draft counts (the batcher also writes the
        registry's labeled counters — the authoritative source the reads
        below prefer; this shadow only carries an obs-disabled server)."""
        p, a = self._local[slot]
        self._local[slot] = (p + proposed, a + accepted)

    def _counts(self, slot: int) -> tuple:
        from picotron_tpu.obs.metrics import NULL_INSTRUMENT

        reg = self.registry
        c = reg.counter("picotron_slot_draft_proposed_total",
                        slot=str(slot))
        if c is NULL_INSTRUMENT:
            return self._local[slot]
        return (c.value,
                reg.counter("picotron_slot_draft_accepted_total",
                            slot=str(slot)).value)

    def _mean_latency(self, kind: str) -> Optional[float]:
        h = self.registry.histogram(
            "picotron_dispatch_seconds",
            "dispatch wall time incl. host sync, by kind", kind=kind)
        r = h.read()
        if r["count"] < self.cfg.latency_min_samples:
            return None
        return r["sum"] / r["count"]

    def _pays(self, g: int, r: float) -> Optional[bool]:
        """Whether speculating at ``g`` with accept rate ``r`` beats the
        blocked-decode alternative on MEASURED dispatch latencies:
        (1 + r*g) tokens per (verify + draft) dispatch vs ``block_len``
        tokens per decode dispatch. None while either histogram is under
        ``latency_min_samples`` — the accept thresholds then decide
        alone (a mixed controller batch never runs decode_block, so
        fresh servers start threshold-only and gain the cost term as
        evidence accumulates)."""
        c_v = self._mean_latency("verify")
        c_d = self._mean_latency("decode")
        if c_v is None or c_d is None:
            return None
        c_draft = self._mean_latency("draft") or 0.0
        return (1.0 + r * g) * c_d > self.block_len * (c_v + c_draft)

    # ---- decision recording ------------------------------------------------

    def _decide(self, action: str) -> None:
        self._decisions[action] = self._decisions.get(action, 0) + 1
        self.registry.counter(
            "picotron_spec_controller_decisions_total",
            "spec controller policy decisions by action",
            action=action).inc()

    @property
    def decisions(self) -> dict:
        """{action: count} over the controller's lifetime (the bench's
        controller-decision counts)."""
        return dict(self._decisions)

    # ---- batcher surface ---------------------------------------------------

    def reset(self, slot: int, tpot_slo_s: Optional[float] = None) -> None:
        """A fresh request took ``slot``: restart it at the optimistic
        full draft with the primary drafter and clean stats.
        ``tpot_slo_s`` (multi-tenant serving) is the request's token-gap
        budget in seconds — a slot whose measured dispatch latency
        cannot afford the full draft width starts at 1 instead of
        ``gmax`` and is capped down each round it overshoots."""
        self._slo[slot] = tpot_slo_s
        self._g[slot] = self.gmax
        if tpot_slo_s is not None and self._over_slo(slot):
            # the measured verify cadence already misses this budget:
            # start at the narrowest useful draft, not the optimistic max
            self._g[slot] = 1
        self._kind[slot] = self.kinds[0]
        self._streak[slot] = 0
        self._idle[slot] = 0
        self._tried[slot] = {self.kinds[0]}
        self._snap[slot] = self._counts(slot)

    def _over_slo(self, slot: int) -> bool:
        """Whether the slot's measured per-dispatch latency (verify +
        draft — the burst gap its client observes) exceeds its TPOT SLO.
        False without an SLO or before the latency histograms hold
        ``latency_min_samples`` — the SLO input engages on EVIDENCE,
        like the controller's cost term."""
        slo = self._slo[slot]
        if slo is None:
            return False
        c_v = self._mean_latency("verify")
        if c_v is None:
            return False
        return c_v + (self._mean_latency("draft") or 0.0) > slo

    def lens(self) -> np.ndarray:
        """Per-slot draft length for the NEXT round [slots] int32."""
        return np.asarray(self._g, np.int32)

    def drafter_kinds(self) -> list:
        """Per-slot drafter kind for the NEXT round."""
        return list(self._kind)

    def spec_len_mean(self, occupied) -> float:
        """Mean effective spec_len over ``occupied`` slot indices (the
        ``picotron_spec_len`` gauge / bench ``spec_len_effective``)."""
        occ = list(occupied)
        if not occ:
            return 0.0
        return float(np.mean([self._g[i] for i in occ]))

    def after_round(self, slot: int) -> None:
        """One occupied slot finished one scheduler round (verify or the
        decode_block fallback): advance its cooloff clock and, once its
        proposal window has filled, evaluate."""
        g = self._g[slot]
        if g == 0:
            self._idle[slot] += 1
            if self.cfg.cooloff and self._idle[slot] >= self.cfg.cooloff:
                # re-probe: traffic may have turned easy; a 1-token draft
                # is the cheapest possible question
                self._g[slot] = 1
                self._idle[slot] = 0
                self._streak[slot] = 0
                self._tried[slot] = {self._kind[slot]}
                self._snap[slot] = self._counts(slot)
                self._decide("probe")
            return
        if g > 1 and self._over_slo(slot):
            # SLO input (multi-tenant serving): the dispatch burst gap
            # misses this slot's token-cadence budget — halve the width
            # now, without waiting for the accept-rate window; ramp-ups
            # re-earn width only once the cadence fits again
            self._g[slot] = g // 2
            self._streak[slot] = 0
            self._decide("slo_cap")
            return
        prop, acc = self._counts(slot)
        sprop, sacc = self._snap[slot]
        if prop - sprop < self.cfg.window:
            return
        r = (acc - sacc) / max(prop - sprop, 1.0)
        self._snap[slot] = (prop, acc)
        direction = (1 if r >= self.cfg.target
                     else -1 if r < self.cfg.low else 0)
        pays = self._pays(g, r)
        if pays is not None:
            if direction > 0 and not self._pays(min(2 * g, self.gmax), r):
                direction = 0  # don't ramp up into a measured loss
            if not pays:
                direction = -1  # measured loss forces down regardless
        if direction == 0:
            self._streak[slot] = 0
            return
        streak = self._streak[slot]
        streak = streak + direction if streak * direction > 0 else direction
        self._streak[slot] = streak
        if abs(streak) < self.cfg.hysteresis:
            return
        self._streak[slot] = 0
        if direction > 0:
            new_g = min(max(1, 2 * g), self.gmax)
            if new_g != g:
                self._g[slot] = new_g
                self._decide("ramp_up")
            return
        if g > 1:
            self._g[slot] = g // 2
            self._decide("ramp_down")
            return
        # at spec_len 1 and still losing: try the other drafter before
        # giving up on speculation for this slot
        untried = [k for k in self.kinds if k not in self._tried[slot]]
        if untried:
            self._kind[slot] = untried[0]
            self._tried[slot].add(untried[0])
            self._snap[slot] = self._counts(slot)
            self._decide("switch_drafter")
            return
        self._g[slot] = 0
        self._idle[slot] = 0
        self._decide("spec_off")
