"""Speculative decoding: cheap host-side drafters for the verify pass.

Classic speculative decoding (Leviathan et al. 2023, "Fast Inference from
Transformers via Speculative Decoding"; Chen et al. 2023, "Accelerating
Large Language Model Decoding with Speculative Sampling") converts decode
from one model pass per token to one pass per ACCEPTED RUN: a cheap
drafter proposes ``gamma`` tokens, one jitted verify dispatch
(engine.verify — the blocked decode program generalized to gamma+1 query
positions per slot) scores them all, and the distribution-preserving
acceptance rule (sampling.speculative_accept) keeps the matching prefix
plus one fresh token. Every dispatch emits between 1 and gamma+1 tokens,
so dispatches-per-token — the host-sync metric bench_decode.py tracks —
drops below 1 whenever anything accepts, and the output distribution is
untouched (bit-identical for greedy, distributionally identical for
sampled; both test-pinned).

This module holds the DRAFT side: a ``Drafter`` needs no device state and
no second model — it proposes from the slot's own token history on the
host, between dispatches. The built-in ``NgramDrafter`` is prompt-lookup
decoding (match the last k tokens against the history, propose what
followed last time): free, and strong exactly where speculation pays —
repetitive continuations, code, retrieval-grounded generation, and the
token loops greedy decoding falls into. The interface is deliberately
tiny so a small draft MODEL can slot in later: wrap its own decode loop in
``propose`` and return gamma tokens.

Acceptance accounting rides in the batcher (``draft_proposed`` /
``draft_accepted`` / ``accept_rate``): an accept-rate of r means the
average dispatch emitted ~1 + r*gamma tokens. Rates near 0 mean the
drafter is guessing blind (speculation costs nothing but the wider verify
dispatch); rates near 1 mean dispatches-per-token approaches
1/(gamma+1).
"""

from __future__ import annotations

import numpy as np


class Drafter:
    """Proposes draft tokens for one slot from its token history.

    Implementations must be DETERMINISTIC functions of ``history`` — the
    acceptance rule (sampling.speculative_accept) treats the proposal as a
    point-mass distribution, which is what makes rejection resampling
    exact. A stochastic drafter (e.g. a sampled draft model) would need
    its per-token proposal probabilities threaded into the accept rule.
    """

    def propose(self, history: np.ndarray, n: int) -> np.ndarray:
        """Return exactly ``n`` proposed continuation tokens (int32) for a
        slot whose tokens so far (prompt + generated, the yet-unwritten
        last token included) are ``history``. Proposals are speculative by
        definition — a bad guess costs nothing but the rejected verify
        columns — so there is no "no proposal" escape hatch; return a
        best-effort guess."""
        raise NotImplementedError


class NgramDrafter(Drafter):
    """Prompt-lookup drafting: match the longest suffix n-gram (``ngram``
    down to 1 tokens) of the history against its earlier occurrences and
    propose the ``n`` tokens that followed the MOST RECENT match. A match
    near the end of the history cycles its continuation (the region from
    the match to the end is exactly the pattern being repeated), which is
    what catches greedy token loops and boilerplate. No match at any
    length falls back to repeating the last token."""

    def __init__(self, ngram: int = 3):
        if ngram < 1:
            raise ValueError("ngram must be >= 1")
        self.ngram = int(ngram)

    def propose(self, history: np.ndarray, n: int) -> np.ndarray:
        h = np.asarray(history, np.int32).reshape(-1)
        if n < 1:
            return np.zeros(0, np.int32)
        if h.size < 2:
            fill = h[-1] if h.size else 0
            return np.full(n, fill, np.int32)
        for k in range(min(self.ngram, h.size - 1), 0, -1):
            suffix = h[-k:]
            # candidate starts i with i + k <= len - 1: the match must have
            # at least one continuation token (the final occurrence — the
            # suffix itself — is excluded by construction)
            windows = np.lib.stride_tricks.sliding_window_view(
                h[: h.size - 1], k)
            hits = np.flatnonzero((windows == suffix).all(axis=1))
            if hits.size:
                cont = h[hits[-1] + k:]
                # cycle the continuation out to n tokens: after a match at
                # the end, the tail IS the expected future of the loop
                return np.resize(cont, n).astype(np.int32)
        return np.full(n, h[-1], np.int32)
