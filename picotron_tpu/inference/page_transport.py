"""KV-page transport: the prefill/decode disaggregation handoff unit.

The PR 7 page pool made "page bytes + block-table rows" the natural unit
of KV movement; this module makes that unit CROSS REPLICAS (DistServe /
Mooncake's disaggregated-serving shape — see docs/SERVING.md
"Disaggregated prefill/decode"). A prefilled request's K/V leaves the
prefill worker as a self-describing payload and lands in a decode
worker's pool byte-exact:

- ``export_prefix(engine, cache, ids)``: radix-match ``ids`` on the
  exporting engine, PIN the matched pages (transient pool references —
  eviction cannot race the serialize), slice each page out of the device
  pool (``paged_kv.slice_page``, one compiled executable for every page)
  and base64 its raw bytes per storage leaf — K/V in the cache's own
  storage dtype, int8 scales, and BOTH representations of the
  ``hot_bf16`` dual pool, so the importer reconstructs the exact bytes,
  never a recompute. The payload carries the covered token ids (the
  radix chunk keys), the page/leaf spec (dtype + shape per leaf), a
  CRC-32 over the raw bytes (a torn transfer fails loudly at import,
  before any page is allocated), and optionally the first sampled token
  (the handoff's seat state).
- ``import_prefix(engine, cache, payload)``: validate the spec against
  the local engine (page_len / dtypes / layout / policy must agree —
  tp-sharding does NOT have to: payloads hold the gathered global bytes,
  and the importing pool re-shards them on write, so tp=1 and tp=2
  replicas interoperate), plan which chunks the local radix is missing,
  allocate exactly those pages (all-or-nothing), write their bytes into
  the pool (``paged_kv.write_page``) and graft them into the radix trie
  (``RadixCache.adopt``) with the cache as sole holder — the same end
  state as a locally prefilled + registered prompt, so a subsequent
  admission radix-hits it with ZERO prefill dispatches for the covered
  prefix.

Refcount discipline (the part chaos drills): an import holds its fresh
pages at refcount 1 until adoption; any failure — exhausted pool, a
device write raising, a CRC mismatch — releases every page of the batch
before propagating, so a failed or retried import can never leak pool
capacity or double-reference a cached page (tests/test_disagg.py pins
this with a write that faults mid-batch).

Transport format: JSON-safe dict (the serving fabric is stdlib HTTP +
JSON end to end). Page bytes ride as base64; for the tiny models the
CPU-proxy fabric serves, payloads are a few KB — on hardware the same
layout maps onto an RDMA/ICI plane without changing the bookkeeping.
"""

from __future__ import annotations

import base64
import zlib

import jax.numpy as jnp
import numpy as np

from picotron_tpu.inference import paged_kv

TRANSPORT_VERSION = 1


class TransportError(ValueError):
    """A payload the local engine cannot accept: wrong version, spec
    mismatch (page_len / dtype / layout / policy), or corrupt bytes
    (CRC). Raised BEFORE any pool page is allocated."""


def _require_paged(engine):
    if engine.paged is None:
        raise TransportError(
            "page transport requires kv_layout='paged' (the contiguous "
            "layout has no pages to ship); set inference.kv_layout: "
            "'paged' on every disaggregated replica")


def transport_spec(engine) -> dict:
    """The engine's page-layout fingerprint: storage-leaf dtypes and
    per-page GLOBAL shapes (tp-sharded pools export/import gathered
    bytes, so the spec is tp-invariant by construction). Exporter and
    importer must agree exactly — byte transport cannot convert."""
    _require_paged(engine)
    m = engine.cfg.model
    kv_shape = [m.num_hidden_layers, engine.page_len,
                m.num_key_value_heads, m.head_dim]
    sc_shape = kv_shape[:-1]
    leaves = {}

    def leaf(name, shape, dtype):
        leaves[name] = {"dtype": str(np.dtype(dtype)),
                        "shape": list(shape)}

    if engine.quantized:
        leaf("k", kv_shape, np.int8)
        leaf("v", kv_shape, np.int8)
        leaf("k_scale", sc_shape, np.float32)
        leaf("v_scale", sc_shape, np.float32)
    else:
        dt = np.dtype(engine.cache_dtype)
        leaf("k", kv_shape, dt)
        leaf("v", kv_shape, dt)
        if engine.page_policy:
            leaf("k_q", kv_shape, np.int8)
            leaf("v_q", kv_shape, np.int8)
            leaf("k_scale", sc_shape, np.float32)
            leaf("v_scale", sc_shape, np.float32)
    return {
        "version": TRANSPORT_VERSION,
        "page_len": engine.page_len,
        "quantized": bool(engine.quantized),
        "policy": bool(engine.page_policy),
        "leaves": leaves,
    }


def check_spec(engine, payload: dict) -> dict:
    """Validate a payload's spec against the local engine; returns the
    local spec. Raises TransportError naming the first disagreement —
    the importer's 400, never a silent byte reinterpretation."""
    local = transport_spec(engine)
    if payload.get("version") != local["version"]:
        raise TransportError(
            f"transport version {payload.get('version')!r} != "
            f"{local['version']} (mixed-build fleet?)")
    for key in ("page_len", "quantized", "policy"):
        if payload.get(key) != local[key]:
            raise TransportError(
                f"transport {key} mismatch: payload {payload.get(key)!r} "
                f"vs local {local[key]!r} — disaggregated replicas must "
                f"share inference.kv_page_len / kv_cache_dtype / "
                f"kv_page_policy")
    if payload.get("leaves") != local["leaves"]:
        raise TransportError(
            f"transport leaf spec mismatch: payload "
            f"{payload.get('leaves')!r} vs local {local['leaves']!r}")
    return local


def export_prefix(engine, cache, ids, first_token=None,
                  tenant: str = "") -> dict:
    """Serialize the longest radix-cached prefix of ``ids`` out of
    ``cache``'s pool. The matched pages are pinned (transient pool refs)
    for the duration; the payload's ``token_ids`` are the covered prefix
    (possibly ending mid-page — the importer adopts the partial tail as
    a partial leaf, exactly what the local radix holds). ``first_token``
    (the handoff seat state) is attached only when the match covers ALL
    of ``ids`` — a partial export cannot vouch for logits it does not
    cover. ``tenant`` scopes the radix lookup AND rides in the payload:
    a tenant's exported chunks can only ever graft into the importer's
    same-tenant radix domain, so the handoff path preserves the
    isolation the salted radix keys establish locally. Returns the
    payload dict; its ``bytes_total`` is the raw (pre-base64) page byte
    count the handoff metrics account."""
    _require_paged(engine)
    p = engine.paged
    ids = [int(t) for t in ids]
    spec = transport_spec(engine)
    pids, matched = p.acquire_prefix(ids, salt=tenant)
    try:
        pages = []
        crc = 0
        total = 0
        if pids:
            # ONE batched gather (pow-2 bucket, NULL-page pads) + ONE
            # host sync however long the prefix: the export runs under
            # the serving front end's dispatch mutex, so per-page
            # round-trips here would stall live decode streams — the
            # exact interference this subsystem exists to remove
            bucket = 1
            while bucket < len(pids):
                bucket *= 2
            pid_arr = np.full(bucket, paged_kv.NULL_PAGE, np.int32)
            pid_arr[:len(pids)] = pids
            batch = engine._gather_pages_jit(cache, pid_arr)
            host = {name: np.asarray(batch[name])
                    for name in spec["leaves"]}
        for i in range(len(pids)):
            enc = {}
            for name in spec["leaves"]:
                raw = np.ascontiguousarray(host[name][i]).tobytes()
                crc = zlib.crc32(raw, crc)
                total += len(raw)
                enc[name] = base64.b64encode(raw).decode("ascii")
            pages.append(enc)
    finally:
        p.release_pages(pids)
    payload = dict(spec)
    payload.update(
        token_ids=ids[:matched],
        pages=pages,
        crc32=crc,
        bytes_total=total,
        tenant=str(tenant),
    )
    if first_token is not None and matched == len(ids):
        payload["first_token"] = int(first_token)
    engine.obs.registry.counter(
        "picotron_handoff_bytes_total",
        "raw KV page bytes moved by the transport, by direction",
        dir="export").inc(total)
    return payload


def _decode_pages(spec: dict, payload: dict) -> list:
    """base64 -> host arrays, CRC-verified. A torn or corrupt transfer
    dies here, before any pool page exists to leak."""
    ids = payload.get("token_ids") or []
    pages_b64 = payload.get("pages") or []
    page_len = spec["page_len"]
    need = -(-len(ids) // page_len) if ids else 0
    if len(pages_b64) != need:
        raise TransportError(
            f"payload covers {len(ids)} tokens but carries "
            f"{len(pages_b64)} pages (need {need})")
    crc = 0
    out = []
    for enc in pages_b64:
        page = {}
        for name, leaf in spec["leaves"].items():
            if name not in enc:
                raise TransportError(f"payload page missing leaf {name!r}")
            try:
                raw = base64.b64decode(enc[name], validate=True)
            except (ValueError, TypeError) as e:
                raise TransportError(f"leaf {name!r}: bad base64: {e}")
            crc = zlib.crc32(raw, crc)
            dt = np.dtype(leaf["dtype"])
            shape = tuple(leaf["shape"])
            expect = dt.itemsize * int(np.prod(shape))
            if len(raw) != expect:
                raise TransportError(
                    f"leaf {name!r}: {len(raw)} bytes, expected {expect} "
                    f"for shape {shape} {dt}")
            page[name] = np.frombuffer(raw, dtype=dt).reshape(shape)
        out.append(page)
    if out and crc != payload.get("crc32"):
        raise TransportError(
            f"payload CRC mismatch ({crc} != {payload.get('crc32')}): "
            f"torn or corrupt page stream")
    return out


def import_prefix(engine, cache, payload) -> tuple:
    """Land a payload's pages in the local pool + radix cache. Only the
    chunks the local trie is MISSING are allocated and written (an
    already-cached prefix costs nothing — remote and local hits
    converge); grafted pages end held by the cache alone, evictable like
    any registered prompt. All-or-nothing on failure: exhaustion, write
    faults, and CRC/spec errors release every allocated page before
    propagating. Returns (cache, info) with info =
    {"tokens", "pages_imported", "created", "bytes_total"}."""
    spec = check_spec(engine, payload)
    p = engine.paged
    ids = [int(t) for t in payload.get("token_ids") or []]
    tenant = str(payload.get("tenant") or "")
    pages = _decode_pages(spec, payload)
    if not ids:
        return cache, {"tokens": 0, "pages_imported": 0, "created": 0,
                       "bytes_total": 0}
    need = p.radix.plan_adopt(ids, salt=tenant)
    if not need:
        # the local radix already covers the whole payload: a remote hit
        # that cost zero pages (the convergent case under affinity churn)
        return cache, {"tokens": len(ids), "pages_imported": 0,
                       "created": 0, "bytes_total": 0}
    pids = p.alloc_import(len(need))
    chunk_pids = dict(zip(need, pids))
    total = 0
    try:
        # pow-2 bucket, padded with NULL-page targets (page 0 is the
        # designated scribble target nothing ever reads): a handful of
        # compiled shapes serve every import size, and the write is ONE
        # cache-donating dispatch — any host-side fault above leaves the
        # cache intact for a clean release-and-retry
        n = len(need)
        bucket = 1
        while bucket < n:
            bucket *= 2
        pid_arr = np.full(bucket, paged_kv.NULL_PAGE, np.int32)
        pid_arr[:n] = pids
        stacked = {}
        for name, leaf in spec["leaves"].items():
            rows = [pages[i][name] for i in need]
            total += sum(arr.nbytes for arr in rows)
            pad = [np.zeros_like(rows[0])] * (bucket - n)
            stacked[name] = jnp.asarray(np.stack(rows + pad))
        cache = engine._write_pages_jit(cache, stacked, pid_arr)
    except Exception:
        # the fault struck before the donating dispatch consumed the
        # cache: the importer's references are the only holders — release
        # them and the pool is exactly as before the import
        p.release_pages(pids)
        raise
    created = p.finish_import(ids, chunk_pids, salt=tenant)
    engine.obs.registry.counter(
        "picotron_handoff_bytes_total",
        "raw KV page bytes moved by the transport, by direction",
        dir="import").inc(total)
    return cache, {"tokens": len(ids), "pages_imported": len(need),
                   "created": created, "bytes_total": total}
