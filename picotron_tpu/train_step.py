"""The jitted 4D-parallel training step.

One ``shard_map`` over the ('dp','pp','cp','tp') mesh contains the whole step:
pipeline schedule (or plain grad-accumulation when pp=1), TP/CP collectives
inside the model, the dp×cp gradient psum, and the optimizer update. This is
the TPU-native collapse of the reference's layered runtime — train_step
(train.py:29-55), the schedule dispatch (train.py:223-231), DataParallelBucket
(data_parallel.py:62-170 + bucket.py), and the optimizer step (train.py:235) —
into a single compiled program. Bucketing dissolves: XLA's scheduler overlaps
the gradient all-reduce with remaining backward compute, which is what the
25 MB buckets + async NCCL achieved by hand.

Gradient sync semantics preserved from the reference:
- grads are averaged over the fused dp×cp group (data_parallel.py:47,83);
- accumulation happens in fp32, cast to the param dtype before the update
  (main_grad policy, data_parallel.py:66,81,161-165);
- sync happens once per step, after the last microbatch
  (require_backward_grad_sync, train.py:40-41).
Additionally, grads of pp-replicated params (embedding, final norm, LM head)
are psum'd over 'pp' — only the owning stage produces nonzero contributions.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.tree_util import tree_flatten_with_path, tree_unflatten

from picotron_tpu.config import Config
from picotron_tpu.models import llama
from picotron_tpu.parallel.pp import no_pipeline, pipeline_1f1b, pipeline_afab
from picotron_tpu.topology import Topology, batch_pspec, named_shardings


def build_optimizer(cfg: Config) -> optax.GradientTransformation:
    t = cfg.training
    parts = []
    if t.grad_clip > 0:
        parts.append(optax.clip_by_global_norm(t.grad_clip))
    parts.append(
        optax.adamw(
            t.learning_rate, b1=t.adam_beta1, b2=t.adam_beta2, eps=t.adam_eps,
            weight_decay=t.weight_decay,
        )
    )
    return optax.chain(*parts)


def _key_name(k) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def opt_pspecs(opt_state_shape, pspecs) -> Any:
    """PartitionSpecs for the optimizer state: any leaf whose tree path ends
    with a parameter's path inherits that parameter's spec (optax mu/nu mirror
    the param tree); scalars (e.g. count) are replicated."""
    is_p = lambda x: isinstance(x, P)
    pflat = tree_flatten_with_path(pspecs, is_leaf=is_p)[0]
    by_path = {tuple(_key_name(k) for k in path): spec for path, spec in pflat}
    oflat, otree = tree_flatten_with_path(opt_state_shape)
    out = []
    for path, leaf in oflat:
        keys = tuple(_key_name(k) for k in path)
        spec = P()
        for i in range(len(keys)):
            if keys[i:] in by_path:
                spec = by_path[keys[i:]]
                break
        out.append(spec)
    return tree_unflatten(otree, out)


def sync_pp_replicated_grads(grads, pspecs):
    """psum over 'pp' for grads of params replicated across stages (embedding,
    final norm, LM head): only the owning stage contributes nonzero grads."""
    flat_g, tree_g = jax.tree.flatten(grads)
    flat_s = jax.tree.leaves(pspecs, is_leaf=lambda x: isinstance(x, P))
    synced = [g if "pp" in s else lax.psum(g, "pp") for g, s in zip(flat_g, flat_s)]
    return tree_unflatten(tree_g, synced)


def init_state(cfg: Config, topo: Topology, seed: int | None = None):
    """Initialize params + optimizer state directly as sharded arrays:
    jit with out_shardings materializes each device's shard without ever
    building the global array — replacing the reference's meta-device init +
    per-rank materialization (checkpoint.py:15-48, 50-102)."""
    seed = cfg.training.seed if seed is None else seed
    pspecs = llama.param_pspecs(cfg.model)
    shardings = named_shardings(topo, pspecs)
    key = jax.random.PRNGKey(seed)
    params = jax.jit(
        partial(llama.init_params, m=cfg.model,
                pp_size=cfg.distributed.pp_size),
        out_shardings=shardings)(key)

    optimizer = build_optimizer(cfg)
    o_shape = jax.eval_shape(optimizer.init, params)
    ospecs = opt_pspecs(o_shape, pspecs)
    oshardings = named_shardings(topo, ospecs)
    opt_state = jax.jit(optimizer.init, out_shardings=oshardings)(params)
    return params, opt_state


def build_train_step(cfg: Config, topo: Topology, multi_step: int = 1):
    """Returns jitted (params, opt_state, tokens, targets) ->
    (params, opt_state, loss). tokens/targets are [M, mbs*dp, seq] int32,
    sharded (None, 'dp', 'cp'). With multi_step=K the returned function runs
    K optimizer steps per call over stacked [K, M, mbs*dp, seq] batches
    (shard with shard_batch_stack) and returns per-step losses [K]."""
    mesh = topo.mesh
    pp = cfg.distributed.pp_size
    engine = cfg.distributed.pp_engine
    pspecs = llama.param_pspecs(cfg.model)
    optimizer = build_optimizer(cfg)
    o_shape = jax.eval_shape(
        optimizer.init,
        jax.eval_shape(partial(llama.init_params, m=cfg.model,
                               pp_size=cfg.distributed.pp_size),
                       jax.random.PRNGKey(0)))
    ospecs = opt_pspecs(o_shape, pspecs)
    bspec = batch_pspec()
    cos, sin = llama.rope_tables(cfg)
    dt = jnp.dtype(cfg.model.dtype)

    def _step(params, opt_state, tokens, targets):
        stage_fn = lambda p, h, tok, tgt: llama.stage_apply(p, h, tok, tgt, cos, sin, cfg)
        h_shape = (tokens.shape[1], tokens.shape[2], cfg.model.hidden_size)
        if pp == 1:
            acc_dt = dt if cfg.training.grad_accum_dtype == "param" else jnp.float32
            loss, grads = no_pipeline(stage_fn, params, tokens, targets,
                                      h_shape, dt, acc_dt)
        elif engine == "1f1b":
            stage_fwd = lambda p, h, tok, tgt: llama.stage_fwd_save(
                p, h, tok, tgt, cos, sin, cfg)
            stage_bwd = lambda p, saved, tok, tgt, dh, dl: llama.stage_bwd(
                p, saved, tok, tgt, dh, dl, cos, sin, cfg)
            loss, grads = pipeline_1f1b(stage_fwd, stage_bwd, params, tokens,
                                        targets, pp, h_shape, dt)
        else:
            loss, grads = pipeline_afab(stage_fn, params, tokens, targets, pp,
                                        h_shape, dt)

        # grad sync: mean over the fused dp×cp group (data_parallel.py:47,83),
        # psum over pp for stage-replicated params, cast fp32 -> param dtype
        # (data_parallel.py:161-165)
        grads = jax.tree.map(lambda g: lax.pmean(g, ("dp", "cp")), grads)
        grads = sync_pp_replicated_grads(grads, pspecs)
        grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads, params)

        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = lax.pmean(loss, ("dp", "cp"))  # logging mean (utils.py:93-98)
        return params, opt_state, loss

    # check_vma=False: the model mixes replicated inputs with axis_index-derived
    # values (stage/cp masks), which the varying-axes checker would require
    # explicit pcasts for at every scan carry; replication correctness is
    # covered by the parallel-vs-single-device equivalence tests instead.
    step = jax.shard_map(
        _step, mesh=mesh,
        in_specs=(pspecs, ospecs, bspec, bspec),
        out_specs=(pspecs, ospecs, P()),
        check_vma=False,
    )
    if multi_step == 1:
        return jax.jit(step, donate_argnums=(0, 1))

    # On-device training loop: scan `step` over `multi_step` stacked batches
    # in ONE dispatch. Removes per-step host round-trips (launch latency +
    # the loss fetch the reference pays every step, train.py:242), which on
    # a remote/tunneled TPU is tens of ms per step. Returns per-step losses.
    def multi(params, opt_state, tokens, targets):
        def body(carry, batch):
            p, o = carry
            p, o, loss = step(p, o, batch[0], batch[1])
            return (p, o), loss

        # unroll on CPU: the step body contains ppermutes, and the XLA CPU
        # runtime's collective rendezvous races across scan iterations
        # (utils.collective_scan_unroll)
        from picotron_tpu.utils import collective_scan_unroll

        (params, opt_state), losses = lax.scan(
            body, (params, opt_state), (tokens, targets),
            unroll=collective_scan_unroll())
        return params, opt_state, losses

    return jax.jit(multi, donate_argnums=(0, 1))


def _place_global(x, sharding):
    """Place a host numpy array carrying the GLOBAL batch onto the mesh.

    Single-process: a plain device_put. Multi-process (a mesh spanning
    hosts): ``jax.device_put`` of a host-local array against a global
    sharding is invalid, so build the jax.Array with
    ``jax.make_array_from_callback`` — every process holds the identical
    global batch (the loader is deterministic: synthetic corpus or
    identically-ordered tokenized dataset, the same every-rank-loads model
    the reference uses, data.py:23-45), and the callback hands XLA exactly
    the shards addressable on this process. Zero cross-host data movement;
    replaces the reference's per-rank sampler slicing (data.py:40-45)."""
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])


def shard_batch(batch, topo: Topology):
    """Place a host numpy batch onto the mesh with (None, 'dp', 'cp')."""
    sh = NamedSharding(topo.mesh, batch_pspec())
    return (_place_global(batch["input_ids"], sh),
            _place_global(batch["target_ids"], sh))


def shard_batch_stack(batches, topo: Topology):
    """Stack K host batches to [K, M, mbs*dp, seq] sharded (None,None,'dp','cp')
    for a multi_step train function."""
    import numpy as np

    sh = NamedSharding(topo.mesh, P(None, *batch_pspec()))
    toks = np.stack([b["input_ids"] for b in batches])
    tgts = np.stack([b["target_ids"] for b in batches])
    return _place_global(toks, sh), _place_global(tgts, sh)
