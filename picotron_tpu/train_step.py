"""The jitted 4D-parallel training step.

One ``shard_map`` over the ('dp','pp','cp','tp') mesh contains the whole step:
pipeline schedule (or plain grad-accumulation when pp=1), TP/CP collectives
inside the model, the dp×cp gradient psum, and the optimizer update. This is
the TPU-native collapse of the reference's layered runtime — train_step
(train.py:29-55), the schedule dispatch (train.py:223-231), DataParallelBucket
(data_parallel.py:62-170 + bucket.py), and the optimizer step (train.py:235) —
into a single compiled program. Bucketing dissolves: XLA's scheduler overlaps
the gradient all-reduce with remaining backward compute, which is what the
25 MB buckets + async NCCL achieved by hand.

Gradient sync semantics preserved from the reference:
- grads are averaged over the fused dp×cp group (data_parallel.py:47,83);
- accumulation happens in fp32, cast to the param dtype before the update
  (main_grad policy, data_parallel.py:66,81,161-165);
- sync happens once per step, after the last microbatch
  (require_backward_grad_sync, train.py:40-41).
Additionally, grads of pp-replicated params (embedding, final norm, LM head)
are psum'd over 'pp' — only the owning stage produces nonzero contributions.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax.tree_util import tree_flatten_with_path, tree_unflatten

from picotron_tpu.config import Config
from picotron_tpu.models import llama
from picotron_tpu.parallel.pp import (
    no_pipeline,
    pipeline_1f1b,
    pipeline_1f1b_interleaved,
    pipeline_afab,
)
from picotron_tpu.parallel.tp import (
    all_gather_dim_invariant,
    reduce_scatter_dim,
)
from picotron_tpu.topology import Topology, batch_pspec, named_shardings
from picotron_tpu.utils import shard_map as shard_map_compat, typeof_vma


def lr_schedule(t):
    """Learning-rate schedule from the training config: optional linear
    warmup from 0 over ``lr_warmup_steps``, then constant / cosine / linear
    decay to ``learning_rate * lr_min_ratio`` over ``lr_decay_steps``
    (default total_train_steps). Returns a plain float for the default
    (constant, no warmup) so the optimizer state keeps the schedule-free
    structure. Beyond the reference, which trains at constant lr
    (train.py:209)."""
    peak = t.learning_rate
    w = t.lr_warmup_steps
    if t.lr_schedule == "constant" and w == 0:
        return peak
    total = t.lr_decay_steps if t.lr_decay_steps is not None else t.total_train_steps
    end = peak * t.lr_min_ratio
    if t.lr_schedule == "constant":
        return optax.join_schedules(
            [optax.linear_schedule(0.0, peak, w),
             optax.constant_schedule(peak)], [w])
    if t.lr_schedule == "cosine":
        return optax.warmup_cosine_decay_schedule(
            0.0, peak, w, max(total, w + 1), end)
    return optax.join_schedules(
        [optax.linear_schedule(0.0, peak, w),
         optax.linear_schedule(peak, end, max(total - w, 1))], [w])


def build_optimizer(cfg: Config) -> optax.GradientTransformation:
    """AdamW with torch defaults (reference train.py:209) and the configured
    lr schedule. Gradient clipping is NOT part of the chain: inside shard_map
    optax.clip_by_global_norm would compute each device's *local* norm —
    different per tp/pp shard, which desyncs replicated params. The step
    applies ``clip_by_global_norm_sharded`` instead (true global norm via
    per-leaf psum over the axes that shard it)."""
    t = cfg.training
    # chain() wrapper kept so the optimizer-state pytree structure matches
    # checkpoints saved when clipping lived inside the chain (grad_clip=0
    # runs — the default — share the (adamw_state,) structure; clip>0
    # checkpoints from before the sharded-clip change need a fresh opt state)
    return optax.chain(optax.adamw(
        lr_schedule(t), b1=t.adam_beta1, b2=t.adam_beta2, eps=t.adam_eps,
        weight_decay=t.weight_decay,
    ))


def _spec_axes(spec) -> tuple:
    axes = []
    for entry in spec:
        if entry is None:
            continue
        axes.extend([entry] if isinstance(entry, str) else list(entry))
    return tuple(axes)


def global_sq_norm_sharded(tree, pspecs):
    """True global squared norm of a sharded tree: each leaf's squared sum
    is psum'd over exactly the axes that shard it (replicated axes excluded
    so nothing is double-counted), so every device computes the same scalar.
    Works for both the param-shaped grad tree (pspecs = llama.param_pspecs)
    and the ZeRO-1 chunk tree (pspecs = zero1_chunk_specs). Shared by the
    global-norm clip and the non-finite gate (any NaN/Inf anywhere in the
    tree — even on a single shard — poisons the psum'd total on EVERY
    device, which is what makes the gate's select globally consistent)."""
    spec_leaves = jax.tree.leaves(pspecs, is_leaf=lambda x: isinstance(x, P))
    total = jnp.float32(0.0)
    for g, spec in zip(jax.tree.leaves(tree), spec_leaves):
        sq = jnp.sum(jnp.square(g.astype(jnp.float32)))
        axes = _spec_axes(spec)
        if axes:
            sq = lax.psum(sq, axes)
        total = total + sq
    return total


def clip_by_global_norm_sharded(grads, pspecs, max_norm):
    """Mesh-aware global-norm clip, matching optax.clip_by_global_norm
    numerics on a single device and keeping replicated params in sync on
    any topology (see global_sq_norm_sharded)."""
    gn = jnp.sqrt(global_sq_norm_sharded(grads, pspecs))
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(gn, 1e-16))
    return jax.tree.map(lambda g: g * scale.astype(g.dtype), grads)


# --------------------------------------------------------------------------- #
# ZeRO-1: dp-sharded optimizer state (beyond-parity; SURVEY §2.3 marks ZeRO
# out of the reference's scope). Each param leaf's local (pp/tp-sharded)
# block is flattened, zero-padded to a multiple of dp, and split into dp
# equal chunks; gradients arrive by reduce-scatter (instead of all-reduce),
# AdamW updates only the local chunk, and the updated chunks all-gather back
# into full params. State memory per device drops by dp at identical
# numerics (pad entries have zero grad and zero param, so their AdamW update
# is exactly zero).
# --------------------------------------------------------------------------- #


def _zero1_chunk_len(n: int, dp: int) -> int:
    return -(-n // dp)


def zero1_chunk_specs(pspecs):
    """PartitionSpec for each flattened chunk leaf: one dimension, tiled over
    'dp' plus every axis that shards the param leaf (canonical order: dp
    outermost, then the param spec's axes in order)."""
    return jax.tree.map(lambda spec: P(("dp", *_spec_axes(spec))), pspecs,
                        is_leaf=lambda x: isinstance(x, P))


def _zero1_scatter(g, dp):
    """Reduce-scatter a local grad block over 'dp': [shape] -> mean chunk
    [ceil(n/dp)]."""
    n = g.size
    c = _zero1_chunk_len(n, dp)
    flat = jnp.pad(g.reshape(-1), (0, dp * c - n))
    return reduce_scatter_dim(flat, "dp", 0) / dp


def _zero1_slice(p, dp):
    """This dp rank's chunk of a local param block."""
    n = p.size
    c = _zero1_chunk_len(n, dp)
    flat = jnp.pad(p.reshape(-1), (0, dp * c - n))
    return lax.dynamic_slice_in_dim(flat, lax.axis_index("dp") * c, c, 0)


def _zero1_unsplit(chunk, like):
    """All-gather updated chunks over 'dp' back into the full local block.
    The invariant-typed gather is what lets the updated params flow back
    out through dp-less out_specs under ``check_vma``; on the checker-off
    build it is the plain public all_gather (see all_gather_dim_invariant)."""
    full = all_gather_dim_invariant(chunk, "dp", 0)
    return full[: like.size].reshape(like.shape)


def zero1_opt_pspecs(cfg: Config, optimizer, pspecs):
    """PartitionSpecs of the dp-chunked optimizer state: eval-shape the
    optimizer on local-chunk-shaped params, then map mu/nu leaves to their
    chunk specs by path suffix (scalars like count stay replicated)."""
    dp = cfg.distributed.dp_size
    p_shape = jax.eval_shape(
        partial(llama.init_params, m=cfg.model, pp_size=cfg.distributed.pp_size,
                interleave=cfg.distributed.pp_interleave),
        jax.random.PRNGKey(0))
    chunk_shape = jax.tree.map(
        lambda p: jax.ShapeDtypeStruct((_zero1_chunk_len(p.size, dp),), p.dtype),
        p_shape)
    o_shape = jax.eval_shape(optimizer.init, chunk_shape)
    return opt_pspecs(o_shape, zero1_chunk_specs(pspecs))


def _key_name(k) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def opt_pspecs(opt_state_shape, pspecs) -> Any:
    """PartitionSpecs for the optimizer state: any leaf whose tree path ends
    with a parameter's path inherits that parameter's spec (optax mu/nu mirror
    the param tree); scalars (e.g. count) are replicated."""
    is_p = lambda x: isinstance(x, P)
    pflat = tree_flatten_with_path(pspecs, is_leaf=is_p)[0]
    by_path = {tuple(_key_name(k) for k in path): spec for path, spec in pflat}
    oflat, otree = tree_flatten_with_path(opt_state_shape)
    out = []
    for path, leaf in oflat:
        keys = tuple(_key_name(k) for k in path)
        spec = P()
        for i in range(len(keys)):
            if keys[i:] in by_path:
                spec = by_path[keys[i:]]
                break
        out.append(spec)
    return tree_unflatten(otree, out)


def sync_sp_norm_grads(grads):
    """Sequence parallelism: norm-weight grads are partial sums over each tp
    rank's seq shard (the norms run on sharded activations) — psum over 'tp'
    completes them. Matmul weight grads are already correct: their activation
    operands are all-gathered to full sequence inside the layer."""
    g = dict(grads)
    layers = dict(g["layers"])
    for k in ("attn_norm", "mlp_norm"):
        layers[k] = lax.psum(layers[k], "tp")
    g["layers"] = layers
    g["final_norm"] = lax.psum(g["final_norm"], "tp")
    return g


def sync_pp_replicated_grads(grads, pspecs):
    """psum over 'pp' for grads of params replicated across stages (embedding,
    final norm, LM head): only the owning stage contributes nonzero grads."""
    flat_g, tree_g = jax.tree.flatten(grads)
    flat_s = jax.tree.leaves(pspecs, is_leaf=lambda x: isinstance(x, P))
    synced = [g if "pp" in s else lax.psum(g, "pp") for g, s in zip(flat_g, flat_s)]
    return tree_unflatten(tree_g, synced)


def init_state(cfg: Config, topo: Topology, seed: int | None = None):
    """Initialize params + optimizer state directly as sharded arrays:
    jit with out_shardings materializes each device's shard without ever
    building the global array — replacing the reference's meta-device init +
    per-rank materialization (checkpoint.py:15-48, 50-102)."""
    seed = cfg.training.seed if seed is None else seed
    pspecs = llama.param_pspecs(cfg.model, fsdp=cfg.distributed.fsdp)
    shardings = named_shardings(topo, pspecs)
    key = jax.random.PRNGKey(seed)
    params = jax.jit(
        partial(llama.init_params, m=cfg.model,
                pp_size=cfg.distributed.pp_size,
                interleave=cfg.distributed.pp_interleave),
        out_shardings=shardings)(key)

    if cfg.distributed.zero1:
        optimizer = build_optimizer(cfg)
        ospecs = zero1_opt_pspecs(cfg, optimizer, pspecs)
        init_fn = lambda p: optimizer.init(
            jax.tree.map(partial(_zero1_slice, dp=cfg.distributed.dp_size), p))
        opt_state = jax.jit(shard_map_compat(
            init_fn, mesh=topo.mesh, in_specs=(pspecs,), out_specs=ospecs,
            check_vma=cfg.distributed.check_vma))(params)
        return params, opt_state

    optimizer = build_optimizer(cfg)
    o_shape = jax.eval_shape(optimizer.init, params)
    ospecs = opt_pspecs(o_shape, pspecs)
    oshardings = named_shardings(topo, ospecs)
    opt_state = jax.jit(optimizer.init, out_shardings=oshardings)(params)
    return params, opt_state


def build_train_step(cfg: Config, topo: Topology, multi_step: int = 1,
                     poison_nonfinite: bool = False):
    """Returns jitted (params, opt_state, tokens, targets) ->
    (params, opt_state, loss). tokens/targets are [M, mbs*dp, seq] int32,
    sharded (None, 'dp', 'cp'). With multi_step=K the returned function runs
    K optimizer steps per call over stacked [K, M, mbs*dp, seq] batches
    (shard with shard_batch_stack) and returns per-step losses [K].

    ``poison_nonfinite=True`` builds the chaos-injection variant: the
    engine's loss and gradients are NaN-poisoned after the backward, exactly
    simulating a numerically blown step (resilience/chaos.py). Used by the
    fault-injection suite to drive the non-finite gate below; never enabled
    in production programs."""
    mesh = topo.mesh
    pp = cfg.distributed.pp_size
    engine = cfg.distributed.pp_engine
    zero1 = cfg.distributed.zero1
    pspecs = llama.param_pspecs(cfg.model, fsdp=cfg.distributed.fsdp)
    optimizer = build_optimizer(cfg)
    if zero1:
        cspecs = zero1_chunk_specs(pspecs)
        ospecs = zero1_opt_pspecs(cfg, optimizer, pspecs)
    else:
        o_shape = jax.eval_shape(
            optimizer.init,
            jax.eval_shape(partial(llama.init_params, m=cfg.model,
                                   pp_size=cfg.distributed.pp_size,
                                   interleave=cfg.distributed.pp_interleave),
                           jax.random.PRNGKey(0)))
        ospecs = opt_pspecs(o_shape, pspecs)
    bspec = batch_pspec()
    cos, sin = llama.rope_tables(cfg)
    dt = jnp.dtype(cfg.model.dtype)

    # with sequence parallelism the residual stream (and so every pipeline
    # boundary tensor) is seq-sharded over 'tp'
    sp_div = (cfg.distributed.tp_size
              if llama.use_sp(cfg) else 1)

    guard = cfg.resilience.nonfinite_guard

    def _step(params, opt_state, tokens, targets):
        params_in, opt_in = params, opt_state
        stage_fn = lambda p, h, tok, tgt: llama.stage_apply(p, h, tok, tgt, cos, sin, cfg)
        h_shape = (tokens.shape[1], tokens.shape[2] // sp_div,
                   cfg.model.hidden_size)
        acc_dt = dt if cfg.training.grad_accum_dtype == "param" else jnp.float32
        if pp == 1:
            loss, grads = no_pipeline(stage_fn, params, tokens, targets,
                                      h_shape, dt, acc_dt)
        elif engine == "1f1b" and cfg.distributed.pp_interleave > 1:
            vch = cfg.distributed.pp_interleave
            stage_fwd = lambda p, h, tok, tgt, fi, la: llama.stage_fwd_save(
                p, h, tok, tgt, cos, sin, cfg, fi, la)
            stage_bwd = lambda p, saved, tok, tgt, dh, dl, fi, la: \
                llama.stage_bwd(p, saved, tok, tgt, dh, dl, cos, sin, cfg,
                                fi, la)
            loss, grads = pipeline_1f1b_interleaved(
                stage_fwd, stage_bwd, params, tokens, targets, pp, vch,
                h_shape, dt, acc_dtype=acc_dt)
        elif engine == "1f1b":
            stage_fwd = lambda p, h, tok, tgt: llama.stage_fwd_save(
                p, h, tok, tgt, cos, sin, cfg)
            stage_bwd = lambda p, saved, tok, tgt, dh, dl: llama.stage_bwd(
                p, saved, tok, tgt, dh, dl, cos, sin, cfg)
            loss, grads = pipeline_1f1b(stage_fwd, stage_bwd, params, tokens,
                                        targets, pp, h_shape, dt,
                                        acc_dtype=acc_dt)
        else:
            loss, grads = pipeline_afab(stage_fn, params, tokens, targets, pp,
                                        h_shape, dt, acc_dtype=acc_dt)

        if poison_nonfinite:
            # chaos build: poison loss AND grads after the engine — the
            # observable signature of a real numeric blow-up (NaN forward
            # implies NaN backward), injected engine-agnostically
            loss = loss + jnp.asarray(jnp.nan, loss.dtype)
            grads = jax.tree.map(
                lambda g: g + jnp.asarray(jnp.nan, g.dtype), grads)

        # Logging mean over the data axes (utils.py:93-98), hoisted before
        # the update so the non-finite gate below can key off the GLOBAL
        # loss (pmean of anything non-finite is non-finite on every device —
        # a shard-local isfinite would desync replicated params). Any pp/tp
        # axis the loss is still TYPED varying over joins the mean as a
        # value-identity replication certificate (the loss is replicated
        # over them by pipeline-psum / CE semantics; a single pmean cannot
        # mix varying and invariant axes, hence the vma-driven set). With
        # the checker off the vma is empty and this is the plain dp x cp
        # mean.
        extra = tuple(a for a in ("pp", "tp") if a in typeof_vma(loss))
        loss = lax.pmean(loss, ("dp", "cp") + extra)

        # grad sync: mean over the fused dp×cp group (data_parallel.py:47,83),
        # psum over pp for stage-replicated params, cast fp32 -> param dtype
        # (data_parallel.py:161-165). With ZeRO-1 the dp share of the mean
        # arrives by reduce-scatter and the update touches only this rank's
        # 1/dp chunk of each (already pp/tp-sharded) param block.
        from picotron_tpu.comm_trace import log as _trace

        if zero1:
            dp = cfg.distributed.dp_size
            _trace("grad all_reduce(mean) + reduce_scatter (zero1)",
                   ("cp", "dp"), jax.tree.leaves(grads)[0],
                   extra=f"leaves={len(jax.tree.leaves(grads))}")
            grads = jax.tree.map(lambda g: lax.pmean(g, "cp"), grads)
            grads = sync_pp_replicated_grads(grads, pspecs)
            if sp_div > 1:
                grads = sync_sp_norm_grads(grads)
            g_chunks = jax.tree.map(partial(_zero1_scatter, dp=dp), grads)
            grads_ok = (jnp.isfinite(global_sq_norm_sharded(g_chunks, cspecs))
                        if guard else None)
            if cfg.training.grad_clip > 0:
                # clip BEFORE the param-dtype downcast: the reference clips
                # fp32 main_grads (data_parallel.py:161-165 casts after sync)
                g_chunks = clip_by_global_norm_sharded(
                    g_chunks, cspecs, cfg.training.grad_clip)
            g_chunks = jax.tree.map(lambda g, p: g.astype(p.dtype),
                                    g_chunks, params)
            p_chunks = jax.tree.map(partial(_zero1_slice, dp=dp), params)
            updates, opt_state = optimizer.update(g_chunks, opt_state, p_chunks)
            p_chunks = optax.apply_updates(p_chunks, updates)
            params = jax.tree.map(_zero1_unsplit, p_chunks, params)
        else:
            if cfg.distributed.fsdp:
                # layer grads arrive dp-SUMMED and dp-sharded (the
                # transpose of decoder_layer's just-in-time all_gather is
                # a reduce-scatter): finish the mean with /dp + a cp
                # pmean. Replicated leaves (embed/final_norm/lm_head)
                # sync as usual.
                dp = cfg.distributed.dp_size
                _trace("fsdp grad reduce_scatter(sum)/dp + cp mean",
                       ("cp",), jax.tree.leaves(grads["layers"])[0],
                       extra=f"leaves={len(jax.tree.leaves(grads))}")
                grads = {
                    **{k: jax.tree.map(
                           lambda g: lax.pmean(g, ("dp", "cp")), v)
                       for k, v in grads.items() if k != "layers"},
                    "layers": jax.tree.map(
                        lambda g: lax.pmean(g, "cp") / dp,
                        grads["layers"]),
                }
            else:
                _trace("grad all_reduce(mean)", ("dp", "cp"),
                       jax.tree.leaves(grads)[0],
                       extra=f"leaves={len(jax.tree.leaves(grads))}")
                grads = jax.tree.map(
                    lambda g: lax.pmean(g, ("dp", "cp")), grads)
            grads = sync_pp_replicated_grads(grads, pspecs)
            if sp_div > 1:
                grads = sync_sp_norm_grads(grads)
            grads_ok = (jnp.isfinite(global_sq_norm_sharded(grads, pspecs))
                        if guard else None)
            if cfg.training.grad_clip > 0:
                # clip the fp32 grads, then downcast — matches the reference's
                # fp32-master-grad clipping order; the pspec-aware clip psums
                # each leaf's sumsq over exactly its sharding axes, so
                # fsdp's dp-sharded layer grads contribute their true
                # global norm
                grads = clip_by_global_norm_sharded(
                    grads, pspecs, cfg.training.grad_clip)
            grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads, params)

            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        if guard:
            # Non-finite gate (resilience): a step with a NaN/Inf loss OR
            # non-finite gradients applies NO param or optimizer update —
            # zeroing grads would not suffice (AdamW still decays weights
            # and moments on zero grads), so the whole new state is
            # where-selected against the old. The grad check matters on its
            # own: a backward-only overflow (finite loss, Inf grad) would
            # otherwise poison params while the loss gate waves it through.
            # On finite steps jnp.where(True, new, old) IS new: numerically
            # identity, bit-for-bit. Both preds are globally reduced (pmean'd
            # loss; per-leaf-psum'd grad norm), identical on every device,
            # so replicated params stay in sync.
            ok = jnp.isfinite(loss) & grads_ok
            keep = lambda new, old: jnp.where(ok, new, old)
            params = jax.tree.map(keep, params, params_in)
            opt_state = jax.tree.map(keep, opt_state, opt_in)
        return params, opt_state, loss

    # The varying-axes checker (distributed.check_vma) is off by default:
    # it is the static-protection DIAGNOSTIC mode (see the config field's
    # rationale — the checker's auto-inserted collectives resequence
    # reductions). The scan carries / cond branches / vjp cotangents all
    # carry explicit vma casts (utils.pvary_like, scan_carry_fixpoint) so
    # that flipping it on is a pure config change; tests/test_check_vma.py
    # builds and runs the step under the checker across topologies.
    step = shard_map_compat(
        _step, mesh=mesh,
        in_specs=(pspecs, ospecs, bspec, bspec),
        out_specs=(pspecs, ospecs, P()),
        check_vma=cfg.distributed.check_vma,
    )
    if multi_step == 1:
        return jax.jit(step, donate_argnums=(0, 1))

    # On-device training loop: scan `step` over `multi_step` stacked batches
    # in ONE dispatch. Removes per-step host round-trips (launch latency +
    # the loss fetch the reference pays every step, train.py:242), which on
    # a remote/tunneled TPU is tens of ms per step. Returns per-step losses.
    def multi(params, opt_state, tokens, targets):
        def body(carry, batch):
            p, o = carry
            p, o, loss = step(p, o, batch[0], batch[1])
            return (p, o), loss

        # unroll on CPU: the step body contains ppermutes, and the XLA CPU
        # runtime's collective rendezvous races across scan iterations
        # (utils.collective_scan_unroll)
        from picotron_tpu.utils import collective_scan_unroll

        (params, opt_state), losses = lax.scan(
            body, (params, opt_state), (tokens, targets),
            unroll=collective_scan_unroll())
        return params, opt_state, losses

    return jax.jit(multi, donate_argnums=(0, 1))


def _place_global(x, sharding):
    """Place a host numpy array carrying the GLOBAL batch onto the mesh.

    Single-process: a plain device_put. Multi-process (a mesh spanning
    hosts): ``jax.device_put`` of a host-local array against a global
    sharding is invalid, so build the jax.Array with
    ``jax.make_array_from_callback`` — every process holds the identical
    global batch (the loader is deterministic: synthetic corpus or
    identically-ordered tokenized dataset, the same every-rank-loads model
    the reference uses, data.py:23-45), and the callback hands XLA exactly
    the shards addressable on this process. Zero cross-host data movement;
    replaces the reference's per-rank sampler slicing (data.py:40-45)."""
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])


def shard_batch(batch, topo: Topology):
    """Place a host numpy batch onto the mesh with (None, 'dp', 'cp')."""
    sh = NamedSharding(topo.mesh, batch_pspec())
    return (_place_global(batch["input_ids"], sh),
            _place_global(batch["target_ids"], sh))


def shard_batch_stack(batches, topo: Topology):
    """Stack K host batches to [K, M, mbs*dp, seq] sharded (None,None,'dp','cp')
    for a multi_step train function."""
    import numpy as np

    sh = NamedSharding(topo.mesh, P(None, *batch_pspec()))
    toks = np.stack([b["input_ids"] for b in batches])
    tgts = np.stack([b["target_ids"] for b in batches])
    return _place_global(toks, sh), _place_global(tgts, sh)
