"""Checkpointing: sharded training checkpoints + HF safetensors interop.

The reference has two mechanisms (picotron/checkpoint.py):

1. **Training checkpoints** — per-(tp_rank, pp_rank) ``.pth`` files whose names
   encode the topology (checkpoint.py:242-244), written only by the dp/cp-rank-0
   replica (checkpoint.py:250-253), holding model + optimizer + step + tokens
   (checkpoint.py:254-260), resumed under the assumption of identical topology
   (checkpoint.py:263). On TPU this collapses into an **orbax** sharded
   checkpoint of the global jax pytrees: each host writes only the shards it
   owns (the dp/cp-rank-0-writes rule is automatic for replicated shards),
   and restore can *change topology* — the saved arrays are global, so loading
   under a different mesh just re-shards them. Step/tokens ride along as JSON.

2. **HF safetensors bootstrap** — per-rank selective reads of a (possibly
   sharded) safetensors model with a picotron⇄HF name map (checkpoint.py:
   213-230) and per-tensor TP slicing (adjust_tensor_size, checkpoint.py:
   150-211). Here the name map becomes ``load_hf_safetensors`` /
   ``save_hf_safetensors`` converting between HF's per-layer (out,in) 2-D
   tensors and our layer-stacked (in,out) pytree; TP/PP slicing needs no code —
   ``jax.device_put`` against the param shardings moves each device's shard.
   The reference's meta-device init context (checkpoint.py:15-48) is replaced
   by ``jax.eval_shape`` + jit with out_shardings (see train_step.init_state).

Note the reference deliberately re-randomizes after loading (checkpoint.py:
99-100 — HF files serve as shape templates for pre-training). We default to
keeping the loaded values; ``checkpoint.hf_bootstrap_reinit: true`` restores
the reference's shape-template semantics (validate names/shapes, keep the
seed-derived random init — see train.py). The untied-lm_head rule is
preserved either way: a missing ``lm_head.weight`` (tied embeddings) gets a
fresh random head (checkpoint.py:88-91, note at :138).
"""

from __future__ import annotations

import json
import os
import threading
import warnings
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from picotron_tpu.config import ModelConfig
from picotron_tpu.models import llama
from picotron_tpu.resilience.retry import retry
from picotron_tpu.topology import Topology, named_shardings

# --------------------------------------------------------------------------- #
# training checkpoints (orbax)
# --------------------------------------------------------------------------- #


def _padded_layout(L: int, pp: int, interleave: int = 1) -> tuple[int, list[int]]:
    """(stacked rows, real-row positions) of the stacked layer axis for a
    (num_hidden_layers, pp_size[, pp_interleave]) layout — [L] with identity
    positions for even contiguous splits, llama.pp_layer_layout otherwise
    (padded uneven splits, chunk-permuted interleaved 1F1B)."""
    if L % pp == 0 and interleave == 1:
        return L, list(range(L))
    K, _, positions = llama.pp_layer_layout(L, pp, interleave)
    return K * pp, positions


def _layout3(layout):
    """Normalize a (L, pp) / (L, pp, interleave) layout tuple to length 3."""
    return (int(layout[0]), int(layout[1]),
            int(layout[2]) if len(layout) > 2 else 1)


def _is_stacked(path) -> bool:
    """Whether a tree path lies under the stacked-layer subtree."""
    return any(
        getattr(k, "key", getattr(k, "name", None)) == "layers"
        for k in path)


def _as_abstract(tree, remap):
    """ShapeDtypeStructs for restoring ``tree``: stacked-layer leaves take
    the SAVED layout's row count when a remap is pending (restored to host,
    re-laid-out, then placed)."""
    from jax.tree_util import tree_flatten_with_path, tree_unflatten

    flat, treedef = tree_flatten_with_path(tree)
    out = []
    for path, x in flat:
        if remap is not None and _is_stacked(path):
            out.append(jax.ShapeDtypeStruct(
                (remap[0],) + tuple(x.shape[1:]), x.dtype))
        else:
            out.append(jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)))
    return tree_unflatten(treedef, out)


def _remap_tree(tree, like, remap):
    """Move each stacked-layer leaf's real rows from the saved layout's
    positions to the restoring layout's, then place against ``like``'s
    shardings."""
    from jax.tree_util import tree_flatten_with_path, tree_unflatten

    src_rows, src_pos, tgt_pos = remap
    flat, treedef = tree_flatten_with_path(tree)
    like_leaves = jax.tree.leaves(like)
    out = []
    for (path, x), ref in zip(flat, like_leaves):
        if _is_stacked(path):
            a = np.asarray(jax.device_get(x))
            dst = np.zeros((ref.shape[0],) + a.shape[1:], a.dtype)
            dst[np.asarray(tgt_pos)] = a[np.asarray(src_pos)]
            sh = getattr(ref, "sharding", None)
            x = jax.device_put(dst, sh) if sh is not None else jnp.asarray(dst)
        out.append(x)
    return tree_unflatten(treedef, out)


class CheckpointManager:
    """Save/resume of (params, opt_state, step, tokens).

    The surface of the reference's CheckpointManager (checkpoint.py:232-278):
    ``save_checkpoint(..., step, tokens)`` every ``save_frequency`` steps and
    ``load_checkpoint`` returning (step, trained_tokens) — topology-portable
    because orbax stores global arrays, not per-rank shards-with-names.
    """

    def __init__(self, save_dir: str, max_to_keep: int = 3,
                 async_save: bool = True, io_attempts: int = 3,
                 io_backoff: float = 0.5, io_jitter: float = 0.25,
                 mirror_dir: str = ""):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(save_dir)
        # Checkpoint replication (resilience.ckpt_mirror_dir): after a save
        # commits, its step directory is copied here (retried, atomic
        # rename), and restores fall back to the mirror when every primary
        # step is unreadable — a second storage tier, so one sick mount
        # cannot strand the run. "" = off. Replication runs on a background
        # thread (it must first wait out the async primary write, and the
        # copy itself can be GBs over a network mount — neither belongs on
        # the training hot path); readers join it first. The mirror keeps
        # the same max_to_keep window as the primary.
        self.mirror_dir = os.path.abspath(mirror_dir) if mirror_dir else ""
        self._mirror_mgr = None
        self._mirror_q = None  # lazily-started worker's step queue
        # _mirror_errs is appended by the mirror worker thread and swapped
        # out by _join_mirror, which readers (restore fallback, close) AND
        # the emergency-save thread can reach concurrently with the worker
        # — the list needs its own lock (picolint PICO-C004)
        self._mirror_errs: list = []
        self._mirror_mu = threading.Lock()
        self._max_to_keep = max_to_keep
        # retrying I/O (resilience): transient NFS/GCS flakes on save/restore
        # are retried with exponential backoff before surfacing
        self._retry = partial(retry, attempts=io_attempts, backoff=io_backoff,
                              jitter=io_jitter)
        # (step, meta) of the checkpoint the last load() actually restored —
        # which, after a corrupt-latest fallback, is NOT the latest step
        self.last_restored_step: Optional[int] = None
        self.last_restored_meta: Optional[dict] = None
        # Async saves: orbax copies device arrays to host synchronously (so
        # donated buffers can be reused by the next step immediately), then
        # writes to disk in a background thread — training only stalls for
        # the D2H copy instead of the full serialization (round-3 VERDICT
        # weak item 6; the reference blocks on torch.save every time,
        # checkpoint.py:246-260).
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=True,
            enable_async_checkpointing=async_save,
        )
        self.manager = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, params, opt_state, trained_tokens: int,
             layout: Optional[tuple[int, int]] = None,
             zero1: Optional[tuple[bool, int]] = None,
             data_meta: Optional[dict] = None) -> None:
        """``layout`` = (num_hidden_layers, pp_size) of the saving run;
        recorded in the metadata so a restore under a different uneven-pp
        padding can remap the stacked layer rows (see ``load``).
        ``zero1`` = (enabled, dp_size): ZeRO-1 chunk shapes depend on dp, so
        the layout is recorded and ``load`` refuses a mismatched restore
        instead of corrupting the optimizer state.
        ``data_meta`` = the data-loader position/geometry
        (MicroBatchDataLoader.state_meta): resume verifies it so a changed
        batch geometry fails loudly instead of training on the wrong data."""
        ocp = self._ocp
        meta = {"step": step, "trained_tokens": int(trained_tokens)}
        if layout is not None:
            lay = _layout3(layout)
            meta["num_hidden_layers"], meta["pp_size"] = lay[0], lay[1]
            meta["pp_interleave"] = lay[2]
        if zero1 is not None:
            meta["zero1"], meta["zero1_dp"] = bool(zero1[0]), int(zero1[1])
        if data_meta is not None:
            meta["data"] = dict(data_meta)
        self._retry(lambda: self.manager.save(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardSave(params),
                opt_state=ocp.args.StandardSave(opt_state),
                meta=ocp.args.JsonSave(meta),
            ),
        ), desc=f"save step {step}")
        # No wait here: with async_save the disk write proceeds in the
        # background; readers go through load()/close(), which both wait.
        # The retry covers the synchronous enqueue (D2H copy + directory
        # setup); a failed background write surfaces at the next wait.
        if self.mirror_dir:
            self._spawn_mirror(step)

    def _spawn_mirror(self, step: int) -> None:
        """Hand ``step`` to the background mirror worker, which waits out
        the async primary write first (mirroring an in-flight write would
        just copy the corruption it exists to survive), then copies +
        atomic-renames, retried. Enqueue only: the training hot path never
        waits on a previous replication (a slow mirror mount makes the
        mirror LAG, not the run stall). Failures warn at the next join;
        readers (restore fallback, wait_until_finished, close) join the
        queue so they only ever see complete steps."""
        import queue
        import threading

        if self._mirror_q is None:
            self._mirror_q = queue.Queue()
            t = threading.Thread(target=self._mirror_worker,
                                 name="ckpt-mirror", daemon=True)
            t.start()
        self._mirror_q.put(step)

    def _mirror_worker(self) -> None:
        import queue

        while True:
            batch = [self._mirror_q.get()]
            try:
                while True:  # drain the backlog accumulated while copying
                    batch.append(self._mirror_q.get_nowait())
            except queue.Empty:
                pass
            try:
                # only the newest max_to_keep backlog steps can survive
                # the mirror's own pruning window: older entries would be
                # full (multi-GB) copies deleted by the very next
                # replication — skip them instead of compounding the lag
                live = batch[-self._max_to_keep:]
                stale = batch[:-self._max_to_keep]
                if stale:
                    warnings.warn(
                        f"checkpoint mirror lagging: skipping superseded "
                        f"steps {stale} (newer saves already queued)",
                        RuntimeWarning)
                for step in live:
                    try:
                        self.manager.wait_until_finished()
                        if not os.path.isdir(os.path.join(self.directory,
                                                          str(step))):
                            # pruned by the primary's max_to_keep window
                            # while it waited: it cannot be replicated —
                            # skip, don't burn retries on a vanished dir
                            raise FileNotFoundError(
                                f"mirror lagging: primary step {step} "
                                f"was pruned before replication")
                        self._retry(partial(self._replicate_step, step),
                                    desc=f"mirror step {step}")
                    except Exception as e:  # noqa: BLE001
                        # warn NOW — an operator must hear that the second
                        # storage tier is stale when it happens, not at the
                        # next reader join (possibly end of run); the
                        # bounded list re-surfaces it to that reader too
                        warnings.warn(
                            f"checkpoint mirror replication of step {step} "
                            f"failed ({type(e).__name__}: {e}); the mirror "
                            f"tier is stale", RuntimeWarning)
                        self._record_mirror_err(e)
            except BaseException as e:  # noqa: BLE001 - the worker must live
                # e.g. warnings promoted to errors (-W error): a dead worker
                # would strand queued entries and deadlock every later
                # _mirror_q.join() (readers, close()) — record and continue
                self._record_mirror_err(e)
            finally:
                for _ in batch:
                    self._mirror_q.task_done()

    def _record_mirror_err(self, e: BaseException) -> None:
        """Retain one replication failure (bounded) for the next reader
        join — under the list's lock: the worker appends here while
        _join_mirror swaps the list out from a reader (or the
        emergency-save) thread."""
        with self._mirror_mu:
            if len(self._mirror_errs) < 8:
                self._mirror_errs.append(e)

    def _join_mirror(self) -> None:
        if self._mirror_q is None:
            return
        self._mirror_q.join()
        with self._mirror_mu:
            errs, self._mirror_errs = self._mirror_errs, []
        for err in errs:
            warnings.warn(
                f"checkpoint mirror replication failed "
                f"({type(err).__name__}: {err}); the mirror tier is stale",
                RuntimeWarning)

    def _replicate_step(self, step: int) -> None:
        """Copy one committed step directory to the mirror tier. The copy
        lands under a temp name and is committed by ``os.rename`` — a
        reader (or a crash mid-copy) never sees a partial mirror step.
        Mirror steps beyond the primary's ``max_to_keep`` window are
        pruned, so the second tier cannot grow without bound."""
        import shutil

        src = os.path.join(self.directory, str(step))
        if not os.path.isdir(src):
            raise FileNotFoundError(f"no committed step dir at {src}")
        os.makedirs(self.mirror_dir, exist_ok=True)
        dst = os.path.join(self.mirror_dir, str(step))
        tmp = os.path.join(self.mirror_dir, f".tmp-{step}")
        shutil.rmtree(tmp, ignore_errors=True)
        shutil.copytree(src, tmp)
        shutil.rmtree(dst, ignore_errors=True)
        os.rename(tmp, dst)
        steps = sorted((int(d) for d in os.listdir(self.mirror_dir)
                        if d.isdigit()), reverse=True)
        for old in steps[self._max_to_keep:]:
            shutil.rmtree(os.path.join(self.mirror_dir, str(old)),
                          ignore_errors=True)
        self._mirror_mgr = None  # step listing changed: rebuild on demand

    def _mirror_manager(self):
        """An orbax manager over the mirror tier, or None when replication
        is off / the mirror holds nothing yet. Joins any in-flight
        replication first — a fallback restore must see complete steps."""
        self._join_mirror()
        if not self.mirror_dir or not os.path.isdir(self.mirror_dir):
            return None
        if self._mirror_mgr is None:
            self._mirror_mgr = self._ocp.CheckpointManager(self.mirror_dir)
        return self._mirror_mgr

    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def _read_meta(self, mgr, step: int) -> dict:
        ocp = self._ocp
        return mgr.restore(
            step, args=ocp.args.Composite(meta=ocp.args.JsonRestore()))["meta"]

    def load(self, params_like, opt_state_like, step: Optional[int] = None,
             layout: Optional[tuple[int, int]] = None,
             zero1: Optional[tuple[bool, int]] = None):
        """Restore into the shardings/dtypes of the given example trees
        (live arrays or ShapeDtypeStructs). Returns
        (params, opt_state, step, trained_tokens).

        When ``step`` is None and the latest step is corrupt or partially
        written, the restore warns and falls back to the previous step
        (resilience: a crash mid-save must never strand the run); the step
        actually restored is reported in the returned tuple and recorded as
        ``last_restored_step``/``last_restored_meta``.

        ``layout`` = (num_hidden_layers, pp_size) of the *restoring* run.
        When the saved metadata records a different uneven-pp pad layout
        (llama.pp_layer_layout), the stacked layer leaves (params['layers']
        and the optimizer moments mirroring them) are restored to host
        memory, their real rows remapped source-layout -> target-layout, and
        the result placed against the example tree's shardings — so orbax
        checkpoints stay topology-portable across uneven splits. Same-layout
        restores (all even splits share the [L] layout) take the direct
        sharded path."""
        ocp = self._ocp
        state: dict = {}

        def guards(meta):
            remap = state["remap"] = self._resolve_remap(meta, layout)
            # ZeRO-1 guard: the dp-chunked optimizer state is dp-specific
            # (leaf shapes = dp * ceil(n_local/dp)) and a 1-D chunk cannot go
            # through the stacked-layer-row remap — refuse a mismatched
            # restore with a real error instead of a shape crash or silent
            # corruption. dp_size only matters when ZeRO-1 is on for either
            # side: non-ZeRO optimizer state is dp-replicated and restores
            # across dp changes fine.
            saved_z = (bool(meta.get("zero1", False)),
                       int(meta.get("zero1_dp", 1)))
            if zero1 is not None:
                want = (bool(zero1[0]), int(zero1[1]))
                mismatch = (saved_z[0] != want[0]) or (
                    saved_z[0] and saved_z[1] != want[1])
                if mismatch:
                    raise ValueError(
                        f"optimizer state was saved with (zero1, dp) = "
                        f"{saved_z} but this run has {want}; ZeRO-1 chunk "
                        f"layouts are dp-specific — restore under the same "
                        f"(zero1, dp_size) or re-shard the optimizer state "
                        f"offline")
            if saved_z[0] and remap is not None:
                raise ValueError(
                    "cannot remap an uneven-pp layer layout on a ZeRO-1 "
                    "checkpoint: the optimizer state is stored as flat dp "
                    "chunks; restore under the saving run's "
                    "(num_hidden_layers, pp_size)")

        def restore(mgr, s, meta):
            remap = state["remap"]
            return mgr.restore(
                s,
                args=ocp.args.Composite(
                    params=ocp.args.StandardRestore(
                        _as_abstract(params_like, remap)),
                    opt_state=ocp.args.StandardRestore(
                        _as_abstract(opt_state_like, remap)),
                ),
            )

        restored, meta = self._fallback_restore(step, guards, restore)
        remap = state["remap"]
        params, opt_state = restored["params"], restored["opt_state"]
        if remap is not None:
            params = _remap_tree(params, params_like, remap)
            opt_state = _remap_tree(opt_state, opt_state_like, remap)
        return (
            params,
            opt_state,
            int(meta["step"]),
            int(meta["trained_tokens"]),
        )

    def _candidate_steps(self, mgr, step: Optional[int]) -> list[int]:
        """Steps to try restoring from ``mgr``, newest first; waits out any
        in-flight async save. An explicit ``step`` is tried alone (the
        caller asked for exactly that state; silently substituting another
        would be worse than failing)."""
        mgr.wait_until_finished()
        if step is not None:
            return [step]
        return sorted(mgr.all_steps(), reverse=True)

    def _fallback_restore(self, step: Optional[int], guards, restore):
        """Try each candidate step newest-first: read meta (retried), run
        ``guards(meta)`` (config-level errors — a wrong topology — propagate;
        an older step cannot fix them), then ``restore(mgr, s, meta)``
        (retried; a failure here means corrupt/partial data, so warn and
        fall back). When every primary step fails, the MIRROR tier
        (``mirror_dir``) gets the same newest-first walk before giving up.
        Returns (restore result, meta).

        A deterministically-corrupt step burns its io_attempts before the
        fallback — deliberate: orbax wraps transient I/O and real corruption
        in overlapping exception types, and losing save_frequency steps of
        work to an unretried network flake costs far more than the seconds
        of re-deserialization here (once per restart, not per step). Tests
        with known-corrupt steps pass io_attempts=1."""
        last_err = None
        tried: list = []
        sources = [("primary", self.manager, self.directory)]
        mirror = self._mirror_manager()
        if mirror is not None:
            sources.append(("mirror", mirror, self.mirror_dir))
        for which, mgr, where in sources:
            candidates = self._candidate_steps(mgr, step)
            if which == "mirror" and candidates:
                warnings.warn(
                    f"no readable checkpoint in {self.directory}; falling "
                    f"back to the mirror {where}", RuntimeWarning)
            for s in candidates:
                tried.append(f"{which}@{s}")
                try:
                    meta = self._retry(partial(self._read_meta, mgr, s),
                                       desc=f"read meta {which}@{s}")
                except Exception as e:
                    last_err = e
                    warnings.warn(
                        f"checkpoint step {s} in {where} has unreadable "
                        f"metadata ({type(e).__name__}: {e}); falling back "
                        f"to the previous step", RuntimeWarning)
                    continue
                guards(meta)
                try:
                    out = self._retry(partial(restore, mgr, s, meta),
                                      desc=f"restore {which}@{s}")
                except Exception as e:
                    last_err = e
                    warnings.warn(
                        f"checkpoint step {s} in {where} is corrupt or "
                        f"partially written ({type(e).__name__}); falling "
                        f"back to the previous step", RuntimeWarning)
                    continue
                self.last_restored_step, self.last_restored_meta = s, meta
                return out, meta
        if not tried:
            raise FileNotFoundError(
                f"no checkpoint found in {self.directory}") from last_err
        raise FileNotFoundError(
            f"no readable checkpoint in {self.directory} (tried "
            f"{tried})") from last_err

    @staticmethod
    def _resolve_remap(meta, layout):
        """(src_rows, src_positions, tgt_positions) when the saved and
        restoring stacked-layer layouts differ, else None."""
        if layout is None or "num_hidden_layers" not in meta:
            return None
        src = (int(meta["num_hidden_layers"]), int(meta["pp_size"]),
               int(meta.get("pp_interleave", 1)))
        layout = _layout3(layout)
        if src[0] != layout[0]:
            raise ValueError(
                f"checkpoint has {src[0]} layers, config wants {layout[0]}")
        src_rows, src_pos = _padded_layout(*src)
        tgt_rows, tgt_pos = _padded_layout(*layout)
        if src_rows != tgt_rows or src_pos != tgt_pos:
            return (src_rows, src_pos, tgt_pos)
        return None

    def load_params(self, params_like, step: Optional[int] = None,
                    layout: Optional[tuple] = None,
                    weight_dtype: str = "bf16"):
        """Params-only restore — the inference path: no optimizer state is
        read (a serving host never allocates the 2x-param AdamW moments).
        ``layout`` is the RESTORING run's (num_hidden_layers, pp_size
        [, interleave]); an inference engine wants ``(L, 1)``, which remaps
        pp-padded or interleave-permuted stacks to the contiguous order the
        decode scan expects. Returns (params, step, trained_tokens).
        Shares the corrupt-latest fallback with ``load``.

        ``weight_dtype="int8"`` quantizes every matmul weight per output
        channel as it comes off the restore (llama.quantize_params —
        checkpoints always store full precision; the int8 form is a
        SERVING format, derived at load). The returned quantized leaves
        carry default placement — place them with ``engine.shard_params``
        (whose pspecs mirror the quantized tree)."""
        if weight_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"unknown weight_dtype {weight_dtype!r} (bf16|int8)")
        ocp = self._ocp
        state: dict = {}

        def guards(meta):
            state["remap"] = self._resolve_remap(meta, layout)

        def restore(mgr, s, meta):
            return mgr.restore(
                s,
                args=ocp.args.Composite(
                    params=ocp.args.StandardRestore(
                        _as_abstract(params_like, state["remap"]))),
            )

        restored, meta = self._fallback_restore(step, guards, restore)
        params = restored["params"]
        if state["remap"] is not None:
            params = _remap_tree(params, params_like, state["remap"])
        if weight_dtype == "int8":
            # leaf-by-leaf eager quantization off the restore: pass a
            # SHARDED ``params_like`` (the dense pspecs — checkpoints
            # store dense) so both the restored tree and the fp32
            # quantization transients stay sharded; a 7B tree never
            # concentrates on one device on its way to int8
            params = llama.quantize_params(params)
        return params, int(meta["step"]), int(meta["trained_tokens"])

    def wait_until_finished(self) -> None:
        self.manager.wait_until_finished()
        self._join_mirror()

    def close(self) -> None:
        # drains any in-flight async save (and replication) first
        self._join_mirror()
        self.manager.close()
        if self._mirror_mgr is not None:
            self._mirror_mgr.close()
            self._mirror_mgr = None


# --------------------------------------------------------------------------- #
# HF safetensors interop
# --------------------------------------------------------------------------- #

# our stacked-tree leaf -> (HF per-layer template, transpose?) — the analogue
# of the reference's name map table (checkpoint.py:213-230). HF linear weights
# are (out_features, in_features); ours are (in, out), hence transpose=True.
_LAYER_MAP = {
    "attn_norm": ("model.layers.{i}.input_layernorm.weight", False),
    "wq": ("model.layers.{i}.self_attn.q_proj.weight", True),
    "wk": ("model.layers.{i}.self_attn.k_proj.weight", True),
    "wv": ("model.layers.{i}.self_attn.v_proj.weight", True),
    "wo": ("model.layers.{i}.self_attn.o_proj.weight", True),
    "mlp_norm": ("model.layers.{i}.post_attention_layernorm.weight", False),
    "w_gate": ("model.layers.{i}.mlp.gate_proj.weight", True),
    "w_up": ("model.layers.{i}.mlp.up_proj.weight", True),
    "w_down": ("model.layers.{i}.mlp.down_proj.weight", True),
}
_TOP_MAP = {
    "embed": ("model.embed_tokens.weight", False),
    "final_norm": ("model.norm.weight", False),
    "lm_head": ("lm_head.weight", True),
}


class _SafetensorsReader:
    """Uniform reader over a single ``model.safetensors`` or a sharded
    ``model.safetensors.index.json`` directory (the two layouts the reference
    handles at checkpoint.py:62-86). File opens are retried (HF snapshots
    commonly live on network mounts); already-open handles are cached."""

    def __init__(self, path: str, io_attempts: int = 3,
                 io_backoff: float = 0.5):
        from safetensors import safe_open

        self._safe_open = safe_open
        self._retry = partial(retry, attempts=io_attempts, backoff=io_backoff)
        self._handles: dict[str, Any] = {}
        if os.path.isfile(path):
            self.index = None
            self._single = path
            self.names = set(self._handle(path).keys())
        else:
            index_file = os.path.join(path, "model.safetensors.index.json")
            single = os.path.join(path, "model.safetensors")
            if os.path.exists(index_file):
                with open(index_file) as f:
                    self.index = json.load(f)["weight_map"]
                self._dir = path
                self._single = None
                self.names = set(self.index)
            elif os.path.exists(single):
                self.index = None
                self._single = single
                self.names = set(self._handle(single).keys())
            else:
                raise FileNotFoundError(
                    f"no model.safetensors[.index.json] under {path}"
                )

    def _file_for(self, name: str) -> str:
        if self.index is None:
            return self._single
        return os.path.join(self._dir, self.index[name])

    def _handle(self, fpath: str):
        if fpath not in self._handles:
            self._handles[fpath] = self._retry(
                lambda: self._safe_open(fpath, framework="np").__enter__(),
                desc=f"open {os.path.basename(fpath)}")
        return self._handles[fpath]

    def get(self, name: str) -> np.ndarray:
        return self._handle(self._file_for(name)).get_tensor(name)

    def get_shape(self, name: str) -> tuple:
        """Header-only shape lookup (``get_slice`` reads zero tensor bytes)."""
        return tuple(self._handle(self._file_for(name))
                     .get_slice(name).get_shape())

    def close(self) -> None:
        for h in self._handles.values():
            h.__exit__(None, None, None)
        self._handles.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def load_hf_safetensors(
    path: str,
    m: ModelConfig,
    topo: Optional[Topology] = None,
    dtype: Optional[str] = None,
    interleave: int = 1,
    fsdp: bool = False,
    weight_dtype: Optional[str] = None,
) -> llama.Params:
    """Build our parameter pytree from an HF-format Llama checkpoint.

    ``path`` is a ``.safetensors`` file or a directory holding one (optionally
    sharded with an index). When ``topo`` is given, leaves are placed with the
    model's param shardings (TP slices / PP stage slices land on their devices
    — the role of adjust_tensor_size + per-rank selective reads in the
    reference, checkpoint.py:150-211).

    ``weight_dtype="int8"`` quantizes each matmul weight per output
    channel AS IT STREAMS off the file (quant_matmul.quantize_weight_host
    per 2-D layer weight, before stacking) — host peak stays near one
    layer's fp copy plus the int8 stack, and the weights land on device
    at ~half the bf16 bytes (embedding/norms stay full precision; scales
    shard over 'tp' with their channels when ``topo`` is given).

    Memory note: the full tree is materialized in host RAM before device_put
    (fine through ~10B params on standard hosts). Multi-host bootstrap of
    larger models should read per-host slices via safetensors ``get_slice``
    against each host's addressable shards — not needed for the reference's
    model ladder (SmolLM-1.7B, Llama-2-7B)."""
    if weight_dtype not in (None, "bf16", "int8"):
        raise ValueError(
            f"unknown weight_dtype {weight_dtype!r} (bf16|int8)")
    quant = weight_dtype == "int8"
    if quant and (interleave > 1 or (topo is not None and topo.pp_size > 1)):
        raise ValueError(
            "weight_dtype='int8' is a serving format: load with the "
            "engine's contiguous pp=1 layout (pad/permuted stacks would "
            "stack per-layer scales into pipeline layouts serving never "
            "reads)")
    if quant and fsdp:
        raise ValueError(
            "weight_dtype='int8' and fsdp are mutually exclusive "
            "(quantized weights serve; FSDP trains)")
    dt = jnp.dtype(dtype or m.dtype)
    L = m.num_hidden_layers
    pp = topo.pp_size if topo is not None else 1

    def stack_layers(per_layer: list[np.ndarray]) -> np.ndarray:
        """HF layer i -> its row in the stacked axis (identity for even
        contiguous splits, zero-padded for uneven ones, chunk-permuted for
        interleaved 1F1B). The fast path requires identity POSITIONS, not
        just rows == L — the interleaved layout is a permutation at the
        same row count."""
        rows, positions = _padded_layout(L, pp, interleave)
        if rows == L and positions == list(range(L)):
            return np.stack(per_layer)
        out = np.zeros((rows,) + per_layer[0].shape, per_layer[0].dtype)
        for g, pos in enumerate(positions):
            out[pos] = per_layer[g]
        return out

    from picotron_tpu.ops.pallas.quant_matmul import quantize_weight_host

    with _SafetensorsReader(path) as reader:

        def grab(tmpl: str, transpose: bool, i: Optional[int] = None) -> np.ndarray:
            t = reader.get(tmpl.format(i=i))
            return np.ascontiguousarray(t.T if transpose else t)

        def grab_layers(k: str, tmpl: str, tr: bool):
            if quant and k in llama.QUANT_WEIGHT_LEAVES:
                # quantize each 2-D (in, out) weight as it streams off the
                # file, then stack the int8 values and per-channel scales
                # separately — one layer's fp copy in RAM at a time. The
                # weight is cast to the MODEL dtype first, exactly like
                # the dense path casts before serving: quantizing the
                # file's own dtype (e.g. an fp16 export under a bf16
                # config) would bake in values the fake-quant parity
                # oracle (quantize-after-cast) can never reproduce
                qs = [quantize_weight_host(grab(tmpl, tr, i).astype(dt))
                      for i in range(L)]
                return {"q": stack_layers([d["q"] for d in qs]),
                        "s": stack_layers([d["s"] for d in qs])}
            return stack_layers([grab(tmpl, tr, i) for i in range(L)])

        params: llama.Params = {
            "embed": grab(*_TOP_MAP["embed"]),
            "layers": {
                k: grab_layers(k, tmpl, tr)
                for k, (tmpl, tr) in _LAYER_MAP.items()
            },
            "final_norm": grab(*_TOP_MAP["final_norm"]),
        }
        if "lm_head.weight" in reader.names:
            params["lm_head"] = grab(*_TOP_MAP["lm_head"])
        else:
            # tied embeddings: the reference always creates a fresh untied head
            # (checkpoint.py:88-91); we untie by copying the embedding
            # transpose, which preserves the tied model's function.
            params["lm_head"] = np.ascontiguousarray(params["embed"].T)
        if quant:
            params["lm_head"] = quantize_weight_host(
                params["lm_head"].astype(dt))

    def to_device(leaf):
        # quantized pairs keep their storage dtypes (int8 values, fp32
        # scales); full-precision leaves cast to the model dtype
        if isinstance(leaf, dict):
            return {k: jnp.asarray(v) for k, v in leaf.items()}
        return jnp.asarray(leaf, dt)

    params = {
        "embed": to_device(params["embed"]),
        "layers": {k: to_device(v) for k, v in params["layers"].items()},
        "final_norm": to_device(params["final_norm"]),
        "lm_head": to_device(params["lm_head"]),
    }
    if topo is not None:
        params = jax.tree.map(
            jax.device_put, params,
            named_shardings(topo, llama.param_pspecs(
                m, fsdp=fsdp,
                weight_dtype="int8" if quant else "bf16")))
    return params


def validate_hf_template(path: str, m: ModelConfig) -> None:
    """Check an HF safetensors checkpoint against the model config using the
    file HEADERS only (names + shapes via ``get_slice`` — zero tensor bytes
    read). This is the validation layer for both bootstrap modes: the
    reference treats HF files as shape templates (checkpoint.py:99-100), so
    a mismatch must be an error before anything is loaded or trained.
    A missing ``lm_head.weight`` is allowed (tied embeddings)."""
    H, I_, V = m.hidden_size, m.intermediate_size, m.vocab_size
    Hq = m.num_attention_heads * m.head_dim
    Hkv = m.num_key_value_heads * m.head_dim
    # our per-layer / top-level leaf shapes; the HF names and the (in,out)
    # -> (out,in) transposes come from the SAME _LAYER_MAP/_TOP_MAP the
    # loader and saver use, so validation cannot drift from them
    ours_layer = {
        "attn_norm": (H,), "wq": (H, Hq), "wk": (H, Hkv), "wv": (H, Hkv),
        "wo": (Hq, H), "mlp_norm": (H,), "w_gate": (H, I_), "w_up": (H, I_),
        "w_down": (I_, H),
    }
    ours_top = {"embed": (V, H), "final_norm": (H,), "lm_head": (H, V)}

    def hf_shape(shape, transpose):
        return tuple(reversed(shape)) if transpose else tuple(shape)

    want = {tmpl: hf_shape(ours_top[k], tr)
            for k, (tmpl, tr) in _TOP_MAP.items()}
    for k, (tmpl, tr) in _LAYER_MAP.items():
        for i in range(m.num_hidden_layers):
            want[tmpl.format(i=i)] = hf_shape(ours_layer[k], tr)
    optional = {_TOP_MAP["lm_head"][0]}  # tied embeddings omit the head

    with _SafetensorsReader(path) as reader:
        missing = sorted(set(want) - reader.names - optional)
        if missing:
            raise ValueError(
                f"{path} does not match the model config: missing tensors "
                f"{missing[:5]}{'...' if len(missing) > 5 else ''}")
        for name in sorted(set(want) & reader.names):
            got = reader.get_shape(name)
            if got != want[name]:
                raise ValueError(
                    f"{path} does not match the model config: {name} has "
                    f"shape {got}, expected {want[name]}")


def save_hf_safetensors(params: llama.Params, path: str, layout) -> None:
    """Export our pytree to a single HF-format safetensors file (inverse of
    the reference's import direction — it only reads; export makes the
    bootstrap test a round trip).

    ``layout`` is REQUIRED and describes the run that produced ``params``:
    either the run's ``Config`` or a ``(num_layers, pp_size[, interleave])``
    tuple (use ``(L, 1)`` for a plain un-padded stack). It cannot be inferred
    from the arrays: an interleave-trained stack is chunk-permuted at
    rows == num_layers with no pad rows, so a wrong/omitted layout would
    silently export layer-scrambled weights (round-3 ADVICE)."""
    from safetensors.numpy import save_file

    from picotron_tpu.ops.pallas.quant_matmul import is_quant_weight

    if is_quant_weight(params.get("lm_head")) or any(
            is_quant_weight(v) for v in params.get("layers", {}).values()):
        raise ValueError(
            "int8-quantized params cannot be exported to HF safetensors "
            "(quantization is a lossy serving format); export from the "
            "full-precision source checkpoint instead")
    if hasattr(layout, "distributed"):  # a Config
        L = layout.model.num_hidden_layers
        pp_size = layout.distributed.pp_size
        interleave = layout.distributed.pp_interleave
    else:
        lay = tuple(layout)
        L, pp_size = int(lay[0]), int(lay[1])
        interleave = int(lay[2]) if len(lay) > 2 else 1

    out: dict[str, np.ndarray] = {}

    def put(name: str, arr, transpose: bool):
        a = np.asarray(jax.device_get(arr))
        out[name] = np.ascontiguousarray(a.T if transpose else a)

    for k, (tmpl, tr) in _TOP_MAP.items():
        put(tmpl, params[k], tr)
    rows = params["layers"]["wq"].shape[0]
    if pp_size == 1 and interleave == 1:
        # cross-check the claimed plain layout: pad rows are exactly zero in
        # every leaf (zero init, zero grads, zero adamw update), so an
        # all-zero attn_norm row means this is really an uneven-pp stack
        norms = np.asarray(jax.device_get(params["layers"]["attn_norm"]))
        if not np.all(np.any(norms != 0, axis=-1)):
            raise ValueError(
                "layer stack contains all-zero (pad) rows — this model was "
                "trained with an uneven pp split; pass the run's real "
                "(num_layers, pp_size) layout so only real layers are "
                "exported")
    exp_rows, positions = _padded_layout(L, pp_size, interleave)
    if exp_rows != rows:
        raise ValueError(
            f"layer stack has {rows} rows but layout (num_layers={L}, "
            f"pp_size={pp_size}) implies {exp_rows} — wrong num_layers/"
            f"pp_size for this params tree")
    for k, (tmpl, tr) in _LAYER_MAP.items():
        for i, pos in enumerate(positions):
            put(tmpl.format(i=i), params["layers"][k][pos], tr)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    save_file(out, path)


def download_model(name: str, out_dir: str) -> str:
    """HF snapshot of the safetensors files (reference utils.py:100-115).
    Offline environments: point configs at a local directory instead."""
    from huggingface_hub import snapshot_download

    return snapshot_download(
        repo_id=name,
        allow_patterns=["*.safetensors", "*.json"],
        local_dir=out_dir,
    )


def model_config_from_hf(path_or_name: str) -> dict:
    """Read an HF config.json into our ModelConfig field names (the reference
    drives model shape from AutoConfig, create_config.py:51-54)."""
    cfg_path = (
        path_or_name
        if path_or_name.endswith(".json")
        else os.path.join(path_or_name, "config.json")
    )
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            hf = json.load(f)
    else:
        from transformers import AutoConfig

        hf = AutoConfig.from_pretrained(path_or_name).to_dict()
    keys = [
        "num_hidden_layers", "num_attention_heads", "num_key_value_heads",
        "hidden_size", "intermediate_size", "vocab_size", "rms_norm_eps",
        "rope_theta", "max_position_embeddings",
    ]
    return {k: hf[k] for k in keys if k in hf}
