#!/usr/bin/env python
"""Repo-root shim matching the reference UX: ``python create_config.py --dp 2 ...``."""

from picotron_tpu.tools.create_config import main

if __name__ == "__main__":
    raise SystemExit(main())
